"""Replica-group assignment + safe rebalance (controller.py).

Reference: InstanceAssignmentDriver / InstanceReplicaGroupPartitionSelector
(pinot-controller/.../assignment/instance/), BaseSegmentAssignment's
replica-group path, and TableRebalancer's min-available-replica stepping
(pinot-controller/.../helix/core/rebalance/TableRebalancer.java)."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from pinot_tpu.cluster import Broker, ClusterController, PropertyStore, ServerInstance
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.spi.data_types import Schema

SCHEMA = Schema.build(
    "stats",
    dimensions=[("team", "STRING"), ("year", "INT")],
    metrics=[("runs", "INT")])

TEAMS = ["BOS", "NYA", "SFN", "LAN"]


def _build_segment(tmp, name, seed, n=400):
    rng = np.random.default_rng(seed)
    cols = {
        "team": np.asarray(TEAMS, dtype=object)[rng.integers(0, len(TEAMS), n)],
        "year": rng.integers(2000, 2010, n).astype(np.int32),
        "runs": rng.integers(0, 100, n).astype(np.int32),
    }
    path = str(tmp / name)
    SegmentBuilder(SCHEMA, segment_name=name).build(cols, path)
    return path, cols


def _mk_cluster(n_servers):
    store = PropertyStore()
    controller = ClusterController(store)
    servers = [ServerInstance(store, f"S{i}", backend="host")
               for i in range(n_servers)]
    for s in servers:
        s.start()
    controller.add_schema(SCHEMA.to_json())
    return store, controller, servers


def test_replica_group_assignment(tmp_path):
    store, controller, servers = _mk_cluster(4)
    try:
        table = controller.create_table({"tableName": "stats", "replication": 2})
        ip = controller.configure_instance_partitions(table, 2)
        groups = [set(g) for g in ip["replicaGroups"]]
        assert len(groups) == 2 and not (groups[0] & groups[1])
        for i in range(6):
            path, _ = _build_segment(tmp_path, f"s{i}", seed=i)
            assigned = controller.add_segment(
                table, f"s{i}", {"location": path, "numDocs": 400})
            # one replica in EACH group
            assert len(assigned) == 2
            assert sum(1 for a in assigned if a in groups[0]) == 1
            assert sum(1 for a in assigned if a in groups[1]) == 1
        # within each group, segments spread across both members
        ideal = store.get(f"/IDEALSTATES/{table}")
        per_inst = {}
        for seg_map in ideal.values():
            for inst in seg_map:
                per_inst[inst] = per_inst.get(inst, 0) + 1
        assert all(c == 3 for c in per_inst.values()), per_inst
    finally:
        for s in servers:
            s.stop()


def test_partition_pinned_assignment(tmp_path):
    store, controller, servers = _mk_cluster(4)
    try:
        table = controller.create_table({"tableName": "stats", "replication": 2})
        controller.configure_instance_partitions(table, 2, num_partitions=2)
        ip = controller.instance_partitions(table)
        picks = {}
        for p in (0, 1, 0, 1):
            name = f"p{p}_{len(picks)}"
            path, _ = _build_segment(tmp_path, name, seed=p)
            assigned = controller.add_segment(table, name, {
                "location": path, "numDocs": 400,
                "partitions": {"team": {"functionName": "murmur",
                                        "numPartitions": 2,
                                        "partitions": [p]}}})
            picks.setdefault(p, set()).add(tuple(sorted(assigned)))
        # same partition id → same instances, different ids → different
        assert all(len(v) == 1 for v in picks.values())
        assert picks[0] != picks[1]
        for p, v in picks.items():
            insts = next(iter(v))
            for g, group in enumerate(ip["replicaGroups"]):
                assert insts[g] in group or insts[1 - g] in group
    finally:
        for s in servers:
            s.stop()


def test_safe_rebalance_zero_failed_queries(tmp_path):
    """Add a server, rebalance onto it while hammering the broker: no
    query may fail and no partial results may appear mid-move."""
    store, controller, servers = _mk_cluster(2)
    broker = Broker(store)
    try:
        table = controller.create_table({"tableName": "stats", "replication": 1})
        all_cols = []
        for i in range(8):
            path, cols = _build_segment(tmp_path, f"s{i}", seed=i)
            controller.add_segment(table, f"s{i}",
                                   {"location": path, "numDocs": 400})
            all_cols.append(cols)
        expect = 400 * 8

        failures, mismatches = [], []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                r = broker.execute_sql("SELECT COUNT(*) FROM stats")
                if r.exceptions:
                    failures.append(r.exceptions)
                elif r.result_table.rows[0][0] != expect:
                    mismatches.append(r.result_table.rows[0][0])

        t = threading.Thread(target=hammer)
        t.start()
        time.sleep(0.2)
        # new capacity arrives; rebalance must move ~1/3 of segments onto it
        s_new = ServerInstance(store, "S2", backend="host")
        s_new.start()
        servers.append(s_new)
        res = controller.rebalance(table, min_available_replicas=1)
        time.sleep(0.3)
        stop.set()
        t.join(timeout=10)

        assert res["status"] == "DONE"
        assert res["moves"] > 0
        assert not failures, failures[:3]
        assert not mismatches, mismatches[:5]
        status = controller.rebalance_status(table)
        assert status["status"] == "DONE"
        assert status["segmentsDone"] == status["segmentsTotal"] > 0
        # loads levelled: every server now hosts 2-3 of the 8 segments
        ideal = store.get(f"/IDEALSTATES/{table}")
        per_inst = {}
        for seg_map in ideal.values():
            for inst in seg_map:
                per_inst[inst] = per_inst.get(inst, 0) + 1
        assert len(per_inst) == 3 and max(per_inst.values()) <= 3, per_inst
    finally:
        stop.set()
        for s in servers:
            s.stop()


def test_rebalance_into_replica_groups(tmp_path):
    """Configuring instance partitions then rebalancing restructures an
    existing table into the replica-group layout without downtime."""
    store, controller, servers = _mk_cluster(4)
    broker = Broker(store)
    try:
        table = controller.create_table({"tableName": "stats", "replication": 2})
        for i in range(4):
            path, _ = _build_segment(tmp_path, f"s{i}", seed=i)
            controller.add_segment(table, f"s{i}",
                                   {"location": path, "numDocs": 400})
        ip = controller.configure_instance_partitions(table, 2)
        res = controller.rebalance(table, min_available_replicas=1)
        assert res["status"] == "DONE"
        groups = [set(g) for g in ip["replicaGroups"]]
        ideal = store.get(f"/IDEALSTATES/{table}")
        for seg, seg_map in ideal.items():
            insts = set(seg_map)
            assert len(insts & groups[0]) == 1, (seg, seg_map)
            assert len(insts & groups[1]) == 1, (seg, seg_map)
        r = broker.execute_sql("SELECT COUNT(*) FROM stats")
        assert not r.exceptions and r.result_table.rows[0][0] == 1600
    finally:
        for s in servers:
            s.stop()


def test_rebalance_skips_consuming_segments(tmp_path):
    """CONSUMING segments sit out of rebalance by default (reference:
    includeConsuming=false) — no state flip to ONLINE, no EV-wait hang."""
    store, controller, servers = _mk_cluster(3)
    try:
        table = controller.create_table(
            {"tableName": "stats", "tableType": "REALTIME", "replication": 1})
        for i in range(4):
            path, _ = _build_segment(tmp_path, f"done{i}", seed=i)
            controller.add_segment(table, f"done{i}",
                                   {"location": path, "numDocs": 400})
        # an active consumer, pinned to S0 (no deep-store location yet)
        store.update(f"/IDEALSTATES/{table}", lambda cur: dict(
            cur or {}, consuming_0={"S0": "CONSUMING"}))
        before = store.get(f"/IDEALSTATES/{table}")["consuming_0"]
        res = controller.rebalance(table, min_available_replicas=1)
        assert res["status"] == "DONE"
        after = store.get(f"/IDEALSTATES/{table}")["consuming_0"]
        assert after == before  # untouched, state still CONSUMING
    finally:
        for s in servers:
            s.stop()


def test_sticky_instance_partitions(tmp_path):
    """Re-running configure_instance_partitions keeps eligible instances in
    their previous groups — new capacity fills gaps, groups don't reshuffle."""
    store, controller, servers = _mk_cluster(4)
    try:
        table = controller.create_table({"tableName": "stats", "replication": 2})
        ip1 = controller.configure_instance_partitions(table, 2)
        ip2 = controller.configure_instance_partitions(table, 2)
        assert ip1["replicaGroups"] == ip2["replicaGroups"]
        # kill one member; its replacement joins, others stay put
        lost = ip1["replicaGroups"][1][1]
        victim = next(s for s in servers if s.instance_id == lost)
        victim.stop()
        s_new = ServerInstance(store, "S9", backend="host")
        s_new.start()
        servers.append(s_new)
        ip3 = controller.configure_instance_partitions(table, 2)
        assert ip3["replicaGroups"][0] == ip1["replicaGroups"][0]
        assert ip3["replicaGroups"][1][0] == ip1["replicaGroups"][1][0]
        assert "S9" in ip3["replicaGroups"][1]
    finally:
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass


def test_tier_relocation_safe(tmp_path):
    """Aged segments relocate to cold-tier servers via the safe two-phase
    path, under continuous queries with zero failures (reference:
    SegmentRelocator + TierConfig)."""
    import time as _time

    store = PropertyStore()
    controller = ClusterController(store)
    hot = [ServerInstance(store, f"H{i}", backend="host",
                          tags=["hot", "DefaultTenant"]) for i in range(2)]
    cold = [ServerInstance(store, f"C{i}", backend="host",
                           tags=["cold"]) for i in range(2)]
    servers = hot + cold
    for s in servers:
        s.start()
    broker = Broker(store)
    try:
        controller.add_schema(SCHEMA.to_json())
        now = int(_time.time() * 1000)
        table = controller.create_table({
            "tableName": "stats", "replication": 2, "serverTag": "hot",
            "tierConfigs": [{"name": "coldTier", "segmentSelectorType": "time",
                             "segmentAge": "7d", "serverTag": "cold"}]})
        for i, age_days in enumerate([1, 2, 30, 40]):
            path, _ = _build_segment(tmp_path, f"s{i}", seed=i)
            controller.add_segment(table, f"s{i}", {
                "location": path, "numDocs": 400,
                "endTimeMs": now - age_days * 86_400_000})
        ideal0 = store.get(f"/IDEALSTATES/{table}")
        assert all(set(m) <= {"H0", "H1"} for m in ideal0.values())

        failures = []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                r = broker.execute_sql("SELECT COUNT(*) FROM stats")
                if r.exceptions or r.result_table.rows[0][0] != 1600:
                    failures.append(r.exceptions or r.result_table.rows)

        t = threading.Thread(target=hammer)
        t.start()
        res = controller.relocate_tiers(table)
        stop.set()
        t.join(timeout=10)
        assert res["status"] == "DONE" and res["moves"] == 4  # 2 segs x 2 reps
        ideal = store.get(f"/IDEALSTATES/{table}")
        assert set(ideal["s2"]) <= {"C0", "C1"}, ideal["s2"]
        assert set(ideal["s3"]) <= {"C0", "C1"}
        assert set(ideal["s0"]) <= {"H0", "H1"}
        assert not failures, failures[:2]
        # idempotent: second run moves nothing
        res2 = controller.relocate_tiers(table)
        assert res2["moves"] == 0
    finally:
        stop.set()
        for s in servers:
            s.stop()


def test_upsert_table_rebalance_requires_instance_partitions(tmp_path):
    """Moving upsert segments without partition-pinned placement would
    split pk partitions across servers — rebalance must refuse."""
    store, controller, servers = _mk_cluster(3)
    try:
        table = controller.create_table({
            "tableName": "stats", "tableType": "REALTIME", "replication": 1,
            "upsertConfig": {"mode": "FULL"}})
        with pytest.raises(RuntimeError, match="upsert"):
            controller.rebalance(table)
        # with instance partitions it proceeds
        controller.configure_instance_partitions(table, 1)
        assert controller.rebalance(table)["status"] == "DONE"
    finally:
        for s in servers:
            s.stop()
