"""Durable no-downtime segment rebalance (cluster/rebalance.py).

Reference: TableRebalancer's minimum-available-replica stepping with a
ZK-persisted job context (pinot-controller/.../helix/core/rebalance/),
RebalanceChecker resuming stuck jobs after controller failover, and the
make-before-break discipline of Helix ideal-state transitions.

Covers: the per-segment move state machine end to end, leader failover
resuming mid-rebalance from the journal, retry/backoff with destination
blacklisting, abort/rollback, the make-before-break and routing
invariants (bit-identical results through the both-replicas-ONLINE
window), the rebalance.move fault point (corrupt destination fetch →
quarantine → repair → move completes), the departure-time HBM eviction
of stacked batch-family views, and the actuator's dead-server /
server-add / health-driven triggers.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from pinot_tpu.cluster import (Broker, ClusterController, PropertyStore,
                               ServerInstance)
from pinot_tpu.cluster.rebalance import (ABORTED, ABORTING, DONE,
                                         IN_PROGRESS, MOVE_CANCELLED,
                                         MOVE_COMPLETED, MOVE_FAILED,
                                         MOVE_PENDING, PARTIAL,
                                         SEEN_SERVERS_PATH,
                                         RebalanceActuator,
                                         RebalanceInProgress,
                                         SegmentRebalancer)
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.spi import faults
from pinot_tpu.spi.data_types import Schema
from pinot_tpu.spi.metrics import (CONTROLLER_METRICS, SERVER_METRICS,
                                   ControllerGauge, ControllerMeter,
                                   ControllerTimer, ServerMeter)

pytestmark = pytest.mark.rebalance

SCHEMA = Schema.build(
    "stats",
    dimensions=[("team", "STRING"), ("year", "INT")],
    metrics=[("runs", "INT")])

TEAMS = ["BOS", "NYA", "SFN", "LAN"]
GROUP_SQL = "SELECT team, SUM(runs) FROM stats GROUP BY team ORDER BY team"


@pytest.fixture(autouse=True)
def _reset_faults():
    yield
    faults.FAULTS.reset()


def _build_segment(tmp, name, seed, n=400):
    rng = np.random.default_rng(seed)
    cols = {
        "team": np.asarray(TEAMS, dtype=object)[rng.integers(0, len(TEAMS), n)],
        "year": rng.integers(2000, 2010, n).astype(np.int32),
        "runs": rng.integers(0, 100, n).astype(np.int32),
    }
    path = str(tmp / name)
    SegmentBuilder(SCHEMA, segment_name=name).build(cols, path)
    return path


def _mk_cluster(n_servers, backend="host"):
    store = PropertyStore()
    controller = ClusterController(store, instance_id="ctl1")
    servers = [ServerInstance(store, f"S{i}", backend=backend)
               for i in range(n_servers)]
    for s in servers:
        s.start()
    controller.add_schema(SCHEMA.to_json())
    return store, controller, servers


def _add_segments(controller, table, tmp_path, n, docs=400):
    for i in range(n):
        path = _build_segment(tmp_path, f"s{i}", seed=i, n=docs)
        controller.add_segment(table, f"s{i}",
                               {"location": path, "numDocs": docs})


def _zombie(store, name):
    """A registered, live-looking server that never converges anything —
    the perfect destination for exercising timeout/blacklist paths."""
    store.set(f"/INSTANCECONFIGS/{name}", {"host": "nowhere", "port": 1,
                                           "tags": ["DefaultTenant"]})
    store.set(f"/LIVEINSTANCES/{name}", {"host": "nowhere", "port": 1},
              ephemeral_owner=name)


def _per_instance(ideal):
    out = {}
    for seg_map in ideal.values():
        for inst in seg_map:
            out[inst] = out.get(inst, 0) + 1
    return out


# -- engine: plan → tick → terminal -------------------------------------------


def test_durable_rebalance_completes_and_levels(tmp_path):
    store, controller, servers = _mk_cluster(2)
    broker = Broker(store)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        _add_segments(controller, table, tmp_path, 6)
        rows_before = broker.execute_sql(GROUP_SQL).result_table.rows

        s_new = ServerInstance(store, "S2", backend="host")
        s_new.start()
        servers.append(s_new)

        rb = SegmentRebalancer(controller, move_timeout_s=10.0)
        started0 = CONTROLLER_METRICS.meter_count(
            ControllerMeter.SEGMENT_MOVES_STARTED)
        done0 = CONTROLLER_METRICS.meter_count(
            ControllerMeter.SEGMENT_MOVES_COMPLETED)
        t_count0, _ = CONTROLLER_METRICS.timer_stats(
            ControllerTimer.SEGMENT_MOVE_MS)
        job = rb.run(table)

        assert job["status"] == DONE
        assert job["segmentsDone"] == job["segmentsTotal"] > 0
        assert all(m["state"] == MOVE_COMPLETED for m in job["movePlan"])
        # the converged ideal state IS the journaled target
        ideal = store.get(f"/IDEALSTATES/{table}")
        assert {s: set(m) for s, m in ideal.items()} == \
            {s: set(m) for s, m in job["target"].items()}
        per_inst = _per_instance(ideal)
        assert len(per_inst) == 3 and max(per_inst.values()) <= 3, per_inst
        # metrics: one start + one completion + one timed sample per move
        n = job["segmentsTotal"]
        assert CONTROLLER_METRICS.meter_count(
            ControllerMeter.SEGMENT_MOVES_STARTED) == started0 + n
        assert CONTROLLER_METRICS.meter_count(
            ControllerMeter.SEGMENT_MOVES_COMPLETED) == done0 + n
        t_count, _ = CONTROLLER_METRICS.timer_stats(
            ControllerTimer.SEGMENT_MOVE_MS)
        assert t_count == t_count0 + n
        assert CONTROLLER_METRICS.gauge_value(
            ControllerGauge.REBALANCE_ACTIVE) == 0
        # /REBALANCE doubles as the rebalanceStatus payload
        status = controller.rebalance_status(table)
        assert status["status"] == DONE
        assert status["segmentsDone"] == status["segmentsTotal"]
        # results bit-identical after the shuffle
        r = broker.execute_sql(GROUP_SQL)
        assert not r.exceptions and r.result_table.rows == rows_before
    finally:
        for s in servers:
            s.stop()


def test_plan_is_minimal_movement_and_dry_run_writes_nothing(tmp_path):
    store, controller, servers = _mk_cluster(3)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        _add_segments(controller, table, tmp_path, 6)
        rb = SegmentRebalancer(controller)
        # already levelled (2/2/2): nothing to plan
        dry = rb.plan(table, dry_run=True)
        assert dry["segmentsTotal"] == 0 and dry["status"] == DONE
        assert store.get(f"/REBALANCE/{table}") is None
        # a real no-op plan journals the terminal job immediately
        job = rb.plan(table)
        assert job["status"] == DONE
        assert store.get(f"/REBALANCE/{table}")["status"] == DONE
    finally:
        for s in servers:
            s.stop()


def test_hot_table_segments_move_first(tmp_path):
    """Broker-published table costs weight the move order: with heat on
    the table, bigger segments lead the plan (weight = docs x heat)."""
    store, controller, servers = _mk_cluster(1)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        # s0..s2 small, s3..s5 big — all land on the only server S0
        for i in range(6):
            docs = 100 if i < 3 else 1600
            path = _build_segment(tmp_path, f"s{i}", seed=i, n=docs)
            controller.add_segment(table, f"s{i}",
                                   {"location": path, "numDocs": docs})
        store.set("/BROKERSTATE/b1", {"tableCostsMs": {"stats": 42.0}})
        for sid in ("S1", "S2"):
            s_new = ServerInstance(store, sid, backend="host")
            s_new.start()
            servers.append(s_new)
        rb = SegmentRebalancer(controller)
        assert rb.table_heat() == {"stats": 42.0}
        # 4 of 6 segments must leave S0; the big ones lead the plan
        job = rb.plan(table, dry_run=True)
        assert job["segmentsTotal"] == 4
        weights = [m["weight"] for m in job["movePlan"]]
        assert weights == sorted(weights, reverse=True)
        assert job["movePlan"][0]["weight"] > job["movePlan"][-1]["weight"]
        big = {"s3", "s4", "s5"}
        assert {m["segment"] for m in job["movePlan"][:3]} == big
    finally:
        for s in servers:
            s.stop()


def test_second_plan_refused_while_active(tmp_path):
    store, controller, servers = _mk_cluster(1)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        _add_segments(controller, table, tmp_path, 2)
        _zombie(store, "Z0")  # destination that never converges
        rb = SegmentRebalancer(controller, move_timeout_s=60.0)
        job = rb.plan(table)
        assert job["status"] == IN_PROGRESS
        with pytest.raises(RebalanceInProgress):
            rb.plan(table)
        rb.abort(table)
    finally:
        for s in servers:
            s.stop()


# -- make-before-break + routing window ---------------------------------------


def test_no_downtime_replicas_never_dip_under_live_queries(tmp_path):
    """The acceptance invariant: while the durable engine moves segments,
    every sampled external view keeps >= 1 ONLINE replica per segment,
    queries stay bit-identical, and nothing is double-counted."""
    store, controller, servers = _mk_cluster(2)
    broker = Broker(store)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        _add_segments(controller, table, tmp_path, 8)
        truth = broker.execute_sql(GROUP_SQL).result_table.rows
        count = broker.execute_sql(
            "SELECT COUNT(*) FROM stats").result_table.rows[0][0]
        assert count == 8 * 400

        dips, failures, mismatches = [], [], []
        stop = threading.Event()

        def watch_views():
            while not stop.is_set():
                view = store.get(f"/EXTERNALVIEW/{table}") or {}
                for seg in store.get(f"/IDEALSTATES/{table}") or {}:
                    online = sum(1 for st in (view.get(seg) or {}).values()
                                 if st == "ONLINE")
                    if online < 1:
                        dips.append(seg)

        def hammer():
            while not stop.is_set():
                r = broker.execute_sql(GROUP_SQL)
                if r.exceptions:
                    failures.append(r.exceptions)
                elif r.result_table.rows != truth:
                    mismatches.append(r.result_table.rows)

        threads = [threading.Thread(target=watch_views),
                   threading.Thread(target=hammer)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        s_new = ServerInstance(store, "S2", backend="host")
        s_new.start()
        servers.append(s_new)
        rb = SegmentRebalancer(controller, move_timeout_s=10.0, max_moves=2)
        job = rb.run(table)
        time.sleep(0.2)
        stop.set()
        for t in threads:
            t.join(timeout=10)

        assert job["status"] == DONE and job["segmentsTotal"] > 0
        assert not dips, dips[:5]
        assert not failures, failures[:3]
        assert not mismatches, mismatches[:2]
    finally:
        stop.set()
        for s in servers:
            s.stop()


def test_overlap_window_routes_each_segment_once(tmp_path):
    """Mid-move both replicas are ONLINE. The broker must pick exactly one
    server per segment: rows bit-identical, counts never doubled."""
    store, controller, servers = _mk_cluster(2)
    broker = Broker(store)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        _add_segments(controller, table, tmp_path, 4)
        truth = broker.execute_sql(GROUP_SQL).result_table.rows

        # freeze the make-before-break window: every segment gains its
        # second replica (the additive phase) and nothing is dropped yet
        segs = list(store.get(f"/IDEALSTATES/{table}"))
        other = {"S0": "S1", "S1": "S0"}

        def add_all(ideal):
            for seg, m in ideal.items():
                src = next(iter(m))
                m[other[src]] = "ONLINE"
            return ideal

        store.update(f"/IDEALSTATES/{table}", add_all)
        deadline = time.time() + 5
        while time.time() < deadline:
            view = store.get(f"/EXTERNALVIEW/{table}") or {}
            if all(len([s for s in (view.get(seg) or {}).values()
                        if s == "ONLINE"]) == 2 for seg in segs):
                break
            time.sleep(0.02)
        view = store.get(f"/EXTERNALVIEW/{table}")
        assert all(len(view[seg]) == 2 for seg in segs), view

        # inside the window: exact rows, exact count (a double-routed
        # segment would double SUM and COUNT), every routed segment on
        # exactly one server
        for _ in range(5):
            r = broker.execute_sql(GROUP_SQL)
            assert not r.exceptions
            assert r.result_table.rows == truth
            c = broker.execute_sql("SELECT COUNT(*) FROM stats")
            assert c.result_table.rows[0][0] == 4 * 400
        routed = broker.routing_table(table)
        assert all(len(hosts) == 2 for hosts in routed.values())
    finally:
        for s in servers:
            s.stop()


# -- failover: the journal IS the rebalance -----------------------------------


def test_leader_failover_resumes_mid_rebalance(tmp_path):
    """Kill the leader mid-rebalance: the standby takes the seat and
    drives the SAME journaled plan to completion — every move COMPLETED
    exactly once, results bit-identical before/during/after."""
    store, c1, servers = _mk_cluster(2)
    c2 = ClusterController(store, instance_id="ctl2")
    broker = Broker(store)
    try:
        table = c1.create_table({"tableName": "stats", "replication": 1})
        _add_segments(c1, table, tmp_path, 6)
        truth = broker.execute_sql(GROUP_SQL).result_table.rows
        s_new = ServerInstance(store, "S2", backend="host")
        s_new.start()
        servers.append(s_new)

        assert c1.is_leader() and not c2.is_leader()
        rb1 = SegmentRebalancer(c1, max_moves=1, move_timeout_s=10.0)
        done0 = CONTROLLER_METRICS.meter_count(
            ControllerMeter.SEGMENT_MOVES_COMPLETED)
        job = rb1.plan(table)
        assert job["segmentsTotal"] >= 2
        # advance until at least one move completed but the job is open
        deadline = time.time() + 10
        while time.time() < deadline:
            rb1.tick()
            j = rb1.job(table)
            states = [m["state"] for m in j["movePlan"]]
            if MOVE_COMPLETED in states and j["status"] == IN_PROGRESS:
                break
            time.sleep(0.02)
        j = rb1.job(table)
        assert j["status"] == IN_PROGRESS
        assert any(m["state"] == MOVE_COMPLETED for m in j["movePlan"])

        # leader dies mid-job (session expiry, not graceful resign)
        c1.leader.disconnect()
        store.expire_session("ctl1")
        c1.leader.stop()
        assert c2.is_leader()
        r = broker.execute_sql(GROUP_SQL)
        assert not r.exceptions and r.result_table.rows == truth  # during

        # the new leader's actuator resumes from the journal
        actuator = RebalanceActuator(
            SegmentRebalancer(c2, max_moves=1, move_timeout_s=10.0))
        deadline = time.time() + 15
        while time.time() < deadline:
            actuator()
            final = store.get(f"/REBALANCE/{table}")
            if final["status"] not in (IN_PROGRESS,):
                break
            time.sleep(0.02)
        final = store.get(f"/REBALANCE/{table}")
        assert final["status"] == DONE, final["status"]
        assert final["jobId"] == job["jobId"]  # same journaled job, resumed
        # every move COMPLETED exactly once: per-move terminal state plus
        # a global completion-meter delta of exactly segmentsTotal
        assert all(m["state"] == MOVE_COMPLETED for m in final["movePlan"])
        assert CONTROLLER_METRICS.meter_count(
            ControllerMeter.SEGMENT_MOVES_COMPLETED) \
            == done0 + final["segmentsTotal"]
        ideal = store.get(f"/IDEALSTATES/{table}")
        assert {s: set(m) for s, m in ideal.items()} == \
            {s: set(m) for s, m in final["target"].items()}
        r = broker.execute_sql(GROUP_SQL)
        assert not r.exceptions and r.result_table.rows == truth  # after
    finally:
        for s in servers:
            s.stop()
        c2.stop()


def test_standby_controller_never_actuates(tmp_path):
    store, c1, servers = _mk_cluster(1)
    c2 = ClusterController(store, instance_id="ctl2")
    try:
        assert not c2.is_leader()
        rb2 = SegmentRebalancer(c2)
        assert rb2.tick() == {"skipped": "standby controller does not actuate"}
        assert RebalanceActuator(rb2)()["skipped"]
    finally:
        for s in servers:
            s.stop()
        c2.stop()


# -- retry / blacklist / abort ------------------------------------------------


def test_dead_destination_blacklisted_then_repicked(tmp_path):
    """A destination that never converges exhausts its attempts, lands on
    the blacklist, and the move retries onto a fresh server — the job
    still finishes DONE."""
    store, controller, servers = _mk_cluster(2)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        # everything on S0 so the plan spreads to {S1, Z0}
        store.delete("/LIVEINSTANCES/S1")
        _add_segments(controller, table, tmp_path, 4)
        store.set("/LIVEINSTANCES/S1", {"host": "h", "port": 1},
                  ephemeral_owner="S1")
        _zombie(store, "Z0")

        rb = SegmentRebalancer(controller, move_timeout_s=0.15,
                               max_attempts=1, backoff_ms=10.0, max_moves=4)
        job = rb.drive(table, timeout_s=20.0) if rb.plan(table) else None
        assert job["status"] == DONE, job
        assert all(m["state"] == MOVE_COMPLETED for m in job["movePlan"])
        # at least one move went through the blacklist path
        blacklisted = [m for m in job["movePlan"] if m["blacklist"]]
        assert blacklisted and all(m["blacklist"] == ["Z0"]
                                   for m in blacklisted)
        ideal = store.get(f"/IDEALSTATES/{table}")
        assert all("Z0" not in m for m in ideal.values())
    finally:
        for s in servers:
            s.stop()


def test_move_fails_partial_when_no_replacement(tmp_path):
    """With no healthy replacement outside the blacklist the move FAILS,
    the job ends PARTIAL, and the additive phase is fully rolled back —
    the table keeps serving from its original replicas."""
    store, controller, servers = _mk_cluster(1)
    broker = Broker(store)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        _add_segments(controller, table, tmp_path, 4)
        truth = broker.execute_sql(GROUP_SQL).result_table.rows
        _zombie(store, "Z0")
        failed0 = CONTROLLER_METRICS.meter_count(
            ControllerMeter.SEGMENT_MOVES_FAILED)

        rb = SegmentRebalancer(controller, move_timeout_s=0.15,
                               max_attempts=1, backoff_ms=10.0)
        rb.plan(table)
        job = rb.drive(table, timeout_s=20.0)
        assert job["status"] == PARTIAL
        failed = [m for m in job["movePlan"] if m["state"] == MOVE_FAILED]
        assert failed and job["failedSegments"]
        assert CONTROLLER_METRICS.meter_count(
            ControllerMeter.SEGMENT_MOVES_FAILED) == failed0 + len(failed)
        ideal = store.get(f"/IDEALSTATES/{table}")
        assert all(set(m) == {"S0"} for m in ideal.values()), ideal
        r = broker.execute_sql(GROUP_SQL)
        assert not r.exceptions and r.result_table.rows == truth
    finally:
        for s in servers:
            s.stop()


def test_abort_rolls_back_inflight_additions(tmp_path):
    store, controller, servers = _mk_cluster(1)
    broker = Broker(store)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        _add_segments(controller, table, tmp_path, 4)
        truth = broker.execute_sql(GROUP_SQL).result_table.rows
        _zombie(store, "Z0")
        rb = SegmentRebalancer(controller, move_timeout_s=60.0, max_moves=2)
        rb.plan(table)
        rb.tick()  # starts moves: Z0 joins the ideal state additively
        ideal_mid = store.get(f"/IDEALSTATES/{table}")
        assert any("Z0" in m for m in ideal_mid.values())

        job = rb.abort(table)
        assert job["status"] == ABORTED
        assert all(m["state"] == MOVE_CANCELLED for m in job["movePlan"])
        ideal = store.get(f"/IDEALSTATES/{table}")
        assert all(set(m) == {"S0"} for m in ideal.values()), ideal
        r = broker.execute_sql(GROUP_SQL)
        assert not r.exceptions and r.result_table.rows == truth
        # a fresh plan is allowed after the abort
        assert rb.plan(table, dry_run=True) is not None
    finally:
        for s in servers:
            s.stop()


# -- rebalance.move fault point (satellite: integrity under movement) ---------


def test_corrupt_move_fetch_quarantines_then_move_completes(tmp_path):
    """faults on rebalance.move: the destination's fetched copy arrives
    corrupt → PR-8 integrity path quarantines (EV ERROR, never ONLINE)
    and auto-repair re-fetches fresh — the move then completes and the
    job ends DONE with exact results throughout."""
    store, controller, servers = _mk_cluster(1)
    broker = Broker(store)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        _add_segments(controller, table, tmp_path, 2)
        truth = broker.execute_sql(GROUP_SQL).result_table.rows
        q0 = SERVER_METRICS.meter_count(ServerMeter.SEGMENTS_QUARANTINED)
        r0 = SERVER_METRICS.meter_count(ServerMeter.SEGMENT_REPAIRS)

        s_new = ServerInstance(store, "S1", backend="host")
        s_new.start()
        servers.append(s_new)
        faults.FAULTS.arm("rebalance.move", kind="corrupt", times=1)
        rb = SegmentRebalancer(controller, move_timeout_s=10.0)
        job = rb.run(table, timeout_s=20.0)

        assert faults.FAULTS.fired("rebalance.move") == 1
        assert job["status"] == DONE
        assert all(m["state"] == MOVE_COMPLETED for m in job["movePlan"])
        assert SERVER_METRICS.meter_count(
            ServerMeter.SEGMENTS_QUARANTINED) == q0 + 1
        assert SERVER_METRICS.meter_count(
            ServerMeter.SEGMENT_REPAIRS) == r0 + 1
        r = broker.execute_sql(GROUP_SQL)
        assert not r.exceptions and r.result_table.rows == truth
    finally:
        for s in servers:
            s.stop()


# -- HBM hygiene on departure (satellite: stacked-view leak) ------------------


def test_drop_named_evicts_views_stacks_and_partials():
    """Unit regression for the departure-time leak: eviction by NAME must
    reclaim the per-segment view, any stacked [S, N] batch-family view
    containing the member, and journaled partials — and hbm_stats must
    return exactly the freed bytes."""
    from pinot_tpu.segment.device_cache import DeviceSegmentCache

    class _Seg:
        num_docs = 64

        def __init__(self, name):
            self.name = name

    cache = DeviceSegmentCache()
    a, b = _Seg("segA"), _Seg("segB")
    va = cache.view(a)
    va._planes[("c", "ids")] = np.zeros(64, np.int32)
    vb = cache.view(b)
    vb._planes[("c", "ids")] = np.ones(64, np.int32)
    sv = cache.stacked_view([a, b])
    sv._planes[("c", "ids")] = np.zeros((2, 64), np.int32)
    cache.put_partial(("fp", "segA"), (np.zeros(8, np.int64),),
                      segment_name="segA")
    assert sv.names == {"segA", "segB"}
    used0 = cache.hbm_stats()["hbmBytesUsed"]
    assert used0 > 0

    freed = cache.drop_named("segA")
    assert freed > 0
    stats = cache.hbm_stats()
    assert stats["hbmBytesUsed"] == used0 - freed
    # segA's view, the shared stack, and segA's partial are gone; segB's
    # own view survives
    assert not cache._stacks and cache.get_partial(("fp", "segA")) is None
    assert cache.hbm_stats()["hbmBytesUsed"] == vb.nbytes()
    assert cache.eviction_stats["views"] >= 1
    assert cache.eviction_stats["stacks"] >= 1
    assert cache.eviction_stats["partials"] >= 1
    # idempotent: a second departure frees nothing
    assert cache.drop_named("segA") == 0


def test_drop_by_object_evicts_name_matched_stacks():
    """A stack built from a PREVIOUS incarnation of the segment (different
    object, same name) must still be evicted when the segment departs."""
    from pinot_tpu.segment.device_cache import DeviceSegmentCache

    class _Seg:
        num_docs = 64

        def __init__(self, name):
            self.name = name

    cache = DeviceSegmentCache()
    old, cur, other = _Seg("segX"), _Seg("segX"), _Seg("segY")
    sv = cache.stacked_view([old, other])
    sv._planes[("c", "ids")] = np.zeros((2, 64), np.int32)
    # the current incarnation is a different object: id()-keyed matching
    # alone would leak the old stack forever
    cache.view(cur)._planes[("c", "ids")] = np.zeros(64, np.int32)
    cache.drop(cur)
    assert not cache._stacks
    assert cache.eviction_stats["stacks"] >= 1


def test_moved_away_segment_leaves_no_stacked_views(tmp_path):
    """Integration: warm a stacked batch-family view on the device cache,
    move one member away via the durable engine, and assert no stack
    containing the departed segment survives on the source."""
    from pinot_tpu.segment.device_cache import GLOBAL_DEVICE_CACHE

    store, controller, servers = _mk_cluster(1, backend="tpu")
    broker = Broker(store)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        _add_segments(controller, table, tmp_path, 2)
        r = broker.execute_sql(GROUP_SQL)  # warms views (and stacks when
        assert not r.exceptions           # the family batches)
        truth = r.result_table.rows

        s_new = ServerInstance(store, "S1", backend="tpu")
        s_new.start()
        servers.append(s_new)
        rb = SegmentRebalancer(controller, move_timeout_s=10.0)
        job = rb.run(table, timeout_s=30.0)
        assert job["status"] == DONE and job["segmentsTotal"] >= 1

        moved = {m["segment"] for m in job["movePlan"]}
        with GLOBAL_DEVICE_CACHE._lock:
            stale = [s.names for s in GLOBAL_DEVICE_CACHE._stacks.values()
                     if s.names & moved]
        assert not stale, stale
        r = broker.execute_sql(GROUP_SQL)
        assert not r.exceptions and r.result_table.rows == truth
    finally:
        for s in servers:
            s.stop()


# -- actuator triggers --------------------------------------------------------


def test_actuator_rebuilds_replicas_after_server_death(tmp_path):
    """Dead-server trigger: replication drops below target → the actuator
    journals a rebuild job and the survivors re-fetch from deep store."""
    store, controller, servers = _mk_cluster(3)
    broker = Broker(store)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 2})
        _add_segments(controller, table, tmp_path, 4)
        truth = broker.execute_sql(GROUP_SQL).result_table.rows

        rb = SegmentRebalancer(controller, move_timeout_s=10.0, max_moves=8)
        actuator = RebalanceActuator(rb)
        assert actuator()["auto"] == {}  # healthy cluster: no trigger

        victim = servers.pop(2)
        victim.stop()
        out = actuator()
        assert out["auto"].get(table, "").startswith("dead-server:")
        job = rb.drive(table, timeout_s=20.0)
        assert job["status"] == DONE and job["trigger"] == "dead-server"
        ideal = store.get(f"/IDEALSTATES/{table}")
        assert all(len(m) == 2 and "S2" not in m for m in ideal.values())
        r = broker.execute_sql(GROUP_SQL)
        assert not r.exceptions and r.result_table.rows == truth
    finally:
        for s in servers:
            s.stop()


def test_actuator_spreads_onto_added_server(tmp_path):
    store, controller, servers = _mk_cluster(2)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        _add_segments(controller, table, tmp_path, 6)
        rb = SegmentRebalancer(controller, move_timeout_s=10.0, max_moves=8)
        actuator = RebalanceActuator(rb)
        assert actuator()["auto"] == {}  # baseline membership observed

        s_new = ServerInstance(store, "S2", backend="host")
        s_new.start()
        servers.append(s_new)
        out = actuator()
        assert out["auto"].get(table, "").startswith("server-add:")
        job = rb.drive(table, timeout_s=20.0)
        assert job["status"] == DONE and job["trigger"] == "server-add"
        assert "S2" in _per_instance(store.get(f"/IDEALSTATES/{table}"))
    finally:
        for s in servers:
            s.stop()


def test_health_drain_respects_hysteresis_and_cooldown(tmp_path,
                                                       monkeypatch):
    """The opt-in health loop drains a straggler only after the anomaly
    persists across scrapes, and the cooldown stops back-to-back drains
    (no flapping)."""
    from pinot_tpu.cluster.periodic import HEALTH_REPORT_PATH

    monkeypatch.setenv("PINOT_TPU_HEALTH_REBALANCE", "1")
    monkeypatch.setenv("PINOT_TPU_REBALANCE_HYSTERESIS", "2")
    monkeypatch.setenv("PINOT_TPU_REBALANCE_COOLDOWN_S", "300")
    store, controller, servers = _mk_cluster(3)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        _add_segments(controller, table, tmp_path, 6)
        rb = SegmentRebalancer(controller, move_timeout_s=10.0, max_moves=8)
        actuator = RebalanceActuator(rb)

        def scrape(instance, at_ms):
            store.set(HEALTH_REPORT_PATH, {
                "checkedAtMs": at_ms,
                "anomalies": [{"type": "straggler", "instance": instance,
                               "detail": "p99 3x fleet"}]})

        scrape("S0", 1000)
        out = actuator()
        assert out["health"]["triggered"] == {}  # streak 1 < hysteresis
        assert store.get(f"/REBALANCE/{table}") is None
        out = actuator()  # same checkedAtMs: NOT new evidence
        assert out["health"].get("streaks", {}).get("S0", 1) == 1

        scrape("S0", 2000)
        out = actuator()
        assert table in out["health"]["triggered"]  # streak 2 → drain
        job = rb.drive(table, timeout_s=20.0)
        assert job["status"] == DONE and job["trigger"] == "health"
        assert job["excluded"] == ["S0"]
        assert "S0" not in _per_instance(store.get(f"/IDEALSTATES/{table}"))

        # cooldown: a fresh anomaly (other instance) may not re-trigger
        scrape("S1", 3000)
        actuator()
        scrape("S1", 4000)
        out = actuator()
        assert out["health"].get("cooldown") is True
        assert out["health"]["triggered"] == {}
    finally:
        for s in servers:
            s.stop()


def test_health_drain_refuses_to_break_replication(tmp_path, monkeypatch):
    from pinot_tpu.cluster.periodic import HEALTH_REPORT_PATH

    monkeypatch.setenv("PINOT_TPU_HEALTH_REBALANCE", "1")
    monkeypatch.setenv("PINOT_TPU_REBALANCE_HYSTERESIS", "1")
    store, controller, servers = _mk_cluster(2)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 2})
        _add_segments(controller, table, tmp_path, 2)
        rb = SegmentRebalancer(controller)
        actuator = RebalanceActuator(rb)
        store.set(HEALTH_REPORT_PATH, {
            "checkedAtMs": 1000,
            "anomalies": [{"type": "hbm-pressure", "instance": "S0"}]})
        out = actuator()
        # draining S0 would leave 1 < replication 2: refused
        assert out["health"]["triggered"] == {}
        assert store.get(f"/REBALANCE/{table}") is None
    finally:
        for s in servers:
            s.stop()


def test_rebalance_checker_defers_to_active_durable_job(tmp_path):
    from pinot_tpu.cluster.periodic import RebalanceChecker

    store, controller, servers = _mk_cluster(3)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 2})
        _add_segments(controller, table, tmp_path, 3)
        # kill a server that hosts something: replication is now broken,
        # but two live servers remain (>= replication) so repair CAN run
        hosted = _per_instance(store.get(f"/IDEALSTATES/{table}"))
        victim = next(s for s in servers if s.instance_id in hosted)
        servers.remove(victim)
        victim.stop()
        store.set(f"/REBALANCE/{table}",
                  {"jobId": "rb_x", "status": IN_PROGRESS, "movePlan": []})
        assert RebalanceChecker(controller)() == {}  # defers
        store.set(f"/REBALANCE/{table}", {"jobId": "rb_x", "status": DONE})
        fixed = RebalanceChecker(controller)()
        assert table in fixed  # terminal job: the checker acts again
    finally:
        for s in servers:
            s.stop()


# -- REST surface -------------------------------------------------------------


def test_rest_rebalance_abort_and_debug(tmp_path):
    import json
    import urllib.request

    from pinot_tpu.cluster.rest import ControllerRestServer

    store, controller, servers = _mk_cluster(1)
    crest = ControllerRestServer(controller)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        _add_segments(controller, table, tmp_path, 2)
        _zombie(store, "Z0")
        crest.rebalancer.move_timeout_s = 60.0

        def post(path):
            req = urllib.request.Request(crest.url + path, data=b"",
                                         method="POST")
            try:
                with urllib.request.urlopen(req) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        def get(path):
            with urllib.request.urlopen(crest.url + path) as resp:
                return json.loads(resp.read())

        # the sync drive cannot finish against a zombie destination: the
        # handler reports the still-active journaled job instead
        crest.rebalancer.plan(table)
        code, body = post("/tables/stats/rebalance")
        assert code == 409 and "IN_PROGRESS" in body["error"]

        dbg = get("/debug/rebalance")
        assert table in dbg["active"]
        assert dbg["knobs"]["maxMoves"] >= 1

        code, body = post("/tables/stats/rebalance/abort")
        assert code == 200 and body["status"] == ABORTED
        dbg = get("/debug/rebalance")
        assert table in dbg["finished"]
        code, _ = post("/tables/nosuch/rebalance/abort")
        assert code == 404
    finally:
        crest.close()
        for s in servers:
            s.stop()


# -- coexistence with the legacy blocking rebalance path ----------------------


def test_legacy_rebalance_refuses_while_engine_job_active(tmp_path):
    """The synchronous controller.rebalance shares /REBALANCE/{table} with
    the engine journal: it must refuse (not overwrite) while a movePlan
    job is mid-flight, or in-flight moves are orphaned."""
    store, controller, servers = _mk_cluster(2)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        _add_segments(controller, table, tmp_path, 2)
        engine_job = {
            "jobId": "rb_engine", "status": IN_PROGRESS,
            "segmentsTotal": 1, "segmentsDone": 0,
            "movePlan": [{"segment": "s0", "adds": {"S1": "ONLINE"},
                          "drops": ["S0"], "state": "ADDING",
                          "attempts": 1, "blacklist": []}]}
        store.set(f"/REBALANCE/{table}", engine_job)
        with pytest.raises(RuntimeError, match="rb_engine"):
            controller.rebalance(table)
        # the journal still holds the engine job, untouched
        assert store.get(f"/REBALANCE/{table}")["jobId"] == "rb_engine"
    finally:
        for s in servers:
            s.stop()


def test_engine_never_ticks_or_finalizes_legacy_job(tmp_path):
    """A legacy (movePlan-less) IN_PROGRESS record belongs to a
    synchronous caller: the engine must not tick it, must not finalize it
    to DONE (that would defeat the RebalanceInProgress guard), and must
    refuse to drive it."""
    store, controller, servers = _mk_cluster(1)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        _add_segments(controller, table, tmp_path, 2)
        legacy = {"jobId": "rb_legacy", "status": IN_PROGRESS,
                  "segmentsTotal": 2, "segmentsDone": 0}
        store.set(f"/REBALANCE/{table}", legacy)
        rb = SegmentRebalancer(controller)
        assert rb.tick() == {}
        rb._maybe_finish_job(table)
        assert store.get(f"/REBALANCE/{table}")["status"] == IN_PROGRESS
        with pytest.raises(RebalanceInProgress):
            rb.drive(table, timeout_s=1.0)
        with pytest.raises(RebalanceInProgress):
            rb.plan(table)
    finally:
        for s in servers:
            s.stop()


def test_rebalance_checker_heals_past_stale_legacy_record(tmp_path):
    """A crash leftover of the synchronous path (IN_PROGRESS, no movePlan)
    must not wedge RebalanceChecker healing forever — only engine journals
    defer it."""
    from pinot_tpu.cluster.periodic import RebalanceChecker

    store, controller, servers = _mk_cluster(3)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 2})
        _add_segments(controller, table, tmp_path, 2)
        hosted = _per_instance(store.get(f"/IDEALSTATES/{table}"))
        victim = next(s for s in servers if s.instance_id in hosted)
        servers.remove(victim)
        victim.stop()
        store.set(f"/REBALANCE/{table}",
                  {"jobId": "rb_stale", "status": IN_PROGRESS,
                   "segmentsTotal": 1, "segmentsDone": 0})
        fixed = RebalanceChecker(controller)()
        assert table in fixed
        live = set(store.children("/LIVEINSTANCES"))
        ideal = store.get(f"/IDEALSTATES/{table}")
        assert all(len([i for i in m if i in live]) >= 2
                   for m in ideal.values())
    finally:
        for s in servers:
            s.stop()


def test_drive_requires_leadership_and_abort_defers_actuation(tmp_path):
    """drive() on a standby must refuse (the leader's actuator owns
    actuation); abort() on a standby journals the ABORTING request but
    leaves the rollback to the leader's next tick."""
    store, c1, servers = _mk_cluster(1)
    c2 = ClusterController(store, instance_id="ctl2")
    try:
        assert c1.is_leader() and not c2.is_leader()
        table = c1.create_table({"tableName": "stats", "replication": 1})
        _add_segments(c1, table, tmp_path, 1)
        with pytest.raises(RuntimeError, match="standby"):
            SegmentRebalancer(c2).drive(table, timeout_s=0.5)

        _zombie(store, "Z0")
        store.update(f"/IDEALSTATES/{table}",
                     lambda cur: {**cur, "s0": {**cur["s0"],
                                                "Z0": "ONLINE"}})
        store.set(f"/REBALANCE/{table}", {
            "jobId": "rb_mid", "status": IN_PROGRESS,
            "segmentsTotal": 1, "segmentsDone": 0,
            "movePlan": [{"segment": "s0", "adds": {"Z0": "ONLINE"},
                          "drops": ["S0"], "state": "ADDING",
                          "attempts": 1, "blacklist": []}]})
        job = SegmentRebalancer(c2).abort(table)
        assert job["status"] == ABORTING  # marked, NOT rolled back
        assert "Z0" in store.get(f"/IDEALSTATES/{table}")["s0"]
        SegmentRebalancer(c1).tick()  # the leader actuates the rollback
        final = store.get(f"/REBALANCE/{table}")
        assert final["status"] == ABORTED
        assert final["movePlan"][0]["state"] == MOVE_CANCELLED
        assert "Z0" not in store.get(f"/IDEALSTATES/{table}")["s0"]
    finally:
        for s in servers:
            s.stop()
        c2.stop()


def test_blacklist_repick_respects_drained_instances(tmp_path):
    """A health-drain job journals its excluded instances: the
    blacklist-exhaustion repick must never choose the very straggler the
    job exists to empty."""
    store, controller, servers = _mk_cluster(3)  # S0 S1 S2
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        _add_segments(controller, table, tmp_path, 1)
        _zombie(store, "Z0")
        store.set(f"/IDEALSTATES/{table}",
                  {"s0": {"S0": "ONLINE", "Z0": "ONLINE"}})
        move = {"segment": "s0", "adds": {"Z0": "ONLINE"}, "drops": ["S0"],
                "state": "ADDING", "attempts": 1, "blacklist": [],
                "attemptStartedMs": 0}
        store.set(f"/REBALANCE/{table}",
                  {"jobId": "rb_drain", "status": IN_PROGRESS,
                   "trigger": "health", "excluded": ["S1"],
                   "segmentsTotal": 1, "segmentsDone": 0,
                   "movePlan": [dict(move)]})
        rb = SegmentRebalancer(controller, max_attempts=1, backoff_ms=1.0)
        rb._retry_move(table, 0, move, now_ms=int(time.time() * 1000),
                       reason="destination timed out")
        m = store.get(f"/REBALANCE/{table}")["movePlan"][0]
        assert m["state"] == MOVE_PENDING
        assert m["blacklist"] == ["Z0"]
        assert list(m["adds"]) == ["S2"]  # S1 is being drained: never picked
    finally:
        for s in servers:
            s.stop()


def test_server_add_trigger_survives_controller_restart(tmp_path):
    """The last-seen live-server set is durable: a server added while no
    actuator is alive (controller outage/failover) still fires a
    server-add spread on the replacement actuator's FIRST tick."""
    store, controller, servers = _mk_cluster(1)
    try:
        table = controller.create_table(
            {"tableName": "stats", "replication": 1})
        _add_segments(controller, table, tmp_path, 4)
        RebalanceActuator(SegmentRebalancer(controller))()
        assert store.get(SEEN_SERVERS_PATH) == ["S0"]

        s1 = ServerInstance(store, "S1", backend="host")
        s1.start()
        servers.append(s1)
        # a FRESH actuator (new controller process) must not re-baseline
        report = RebalanceActuator(SegmentRebalancer(controller))()
        assert any(str(v).startswith("server-add:")
                   for v in report["auto"].values()), report
        assert store.get(f"/REBALANCE/{table}")["trigger"] == "server-add"
        assert store.get(SEEN_SERVERS_PATH) == ["S0", "S1"]
    finally:
        for s in servers:
            s.stop()
