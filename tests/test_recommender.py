"""Config recommender + cluster summary endpoint tests (reference:
pinot-controller recommender rule tests)."""

import json
import urllib.request

import pytest

from pinot_tpu.cluster import ClusterController, PropertyStore
from pinot_tpu.cluster.recommender import analyze_queries, recommend
from pinot_tpu.cluster.rest import ControllerRestServer
from pinot_tpu.spi.data_types import Schema

SCHEMA = Schema.build(
    "clicks",
    dimensions=[("country", "STRING"), ("userId", "STRING"),
                ("url", "STRING"), ("device", "STRING")],
    metrics=[("views", "LONG"), ("cost", "DOUBLE")],
    date_times=[("ts", "TIMESTAMP")])

QUERIES = [
    {"sql": "SELECT COUNT(*) FROM clicks WHERE country = 'us'", "freq": 5},
    {"sql": "SELECT SUM(views) FROM clicks WHERE country = 'uk' AND "
            "ts > 1000", "freq": 2},
    {"sql": "SELECT device, SUM(views), SUM(cost) FROM clicks "
            "GROUP BY device", "freq": 4},
    {"sql": "SELECT COUNT(*) FROM clicks WHERE userId = 'u1'", "freq": 1},
]

CARDS = {"country": 200, "userId": 5_000_000, "url": 9_000_000,
         "device": 12, "ts": 8_000_000}


def test_analyze_queries():
    stats = analyze_queries(QUERIES)
    assert stats["eq_filters"]["country"] == pytest.approx(7 / 12)
    assert stats["range_filters"]["ts"] == pytest.approx(2 / 12)
    assert stats["group_by"]["device"] == pytest.approx(4 / 12)
    assert "sum(views)" in stats["aggregations"]


def test_recommendations():
    rec = recommend(SCHEMA, queries=QUERIES, cardinalities=CARDS,
                    num_rows=10_000_000, qps=50)
    idx = rec.indexing
    # country dominates equality filters → sorted column
    assert idx["sortedColumn"] == "country"
    # userId: equality-filtered + high cardinality → bloom
    assert "userId" in idx.get("bloomFilterColumns", [])
    # userId is too high-cardinality for postings: bloom only, no inverted
    assert "userId" not in idx.get("invertedIndexColumns", [])
    # ts range-filtered → range index
    assert "ts" in idx.get("rangeIndexColumns", [])
    # url: near-unique, never filtered → raw + LZ4
    assert idx.get("noDictionaryColumns") == ["url"]
    assert idx.get("compressionConfigs", {}).get("url") == "LZ4"
    # device group-by + aggs → star tree
    st = idx.get("starTreeIndexConfigs")
    assert st and st[0]["dimensionsSplitOrder"] == ["device"]
    assert rec.partition_column == "country"
    assert len(rec.rationale) >= 5


def test_recommender_and_summary_endpoints(tmp_path):
    store = PropertyStore()
    controller = ClusterController(store)
    controller.add_schema(SCHEMA.to_json())
    controller.create_table({"tableName": "clicks", "replication": 1})
    rest = ControllerRestServer(controller)
    try:
        body = json.dumps({"schemaName": "clicks", "queries": QUERIES,
                           "cardinalities": CARDS,
                           "numRows": 10_000_000}).encode()
        req = urllib.request.Request(
            rest.url + "/recommender", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            out = json.loads(r.read())
        assert out["tableIndexConfig"]["sortedColumn"] == "country"
        assert out["rationale"]

        with urllib.request.urlopen(rest.url + "/cluster/summary") as r:
            summary = json.loads(r.read())
        assert "clicks_OFFLINE" in summary["tables"]
        assert summary["schemas"] == ["clicks"]

        with urllib.request.urlopen(rest.url + "/") as r:
            assert r.headers.get("Content-Type", "").startswith("text/html")
            page = r.read().decode()
        assert "<h1>Cluster</h1>" in page
        assert "clicks_OFFLINE" in page
    finally:
        rest.close()
