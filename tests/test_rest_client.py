"""REST API + Python client + CLI tests."""

from __future__ import annotations

import json
import urllib.request

import numpy as np
import pytest

from pinot_tpu.client import PinotClientError, connect
from pinot_tpu.cluster import Broker, ClusterController, PropertyStore, ServerInstance
from pinot_tpu.cluster.rest import BrokerRestServer, ControllerRestServer
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.spi.data_types import Schema

SCHEMA = Schema.build(
    "web", dimensions=[("path", "STRING")], metrics=[("hits", "INT")])


@pytest.fixture()
def stack(tmp_path):
    store = PropertyStore()
    controller = ClusterController(store)
    server = ServerInstance(store, "Server_0", backend="host")
    server.start()
    broker = Broker(store)
    controller.add_schema(SCHEMA.to_json())
    table = controller.create_table({"tableName": "web", "replication": 1})
    cols = {"path": np.asarray(["/a", "/b", "/a", "/c"], dtype=object),
            "hits": np.asarray([1, 2, 3, 4], dtype=np.int32)}
    SegmentBuilder(SCHEMA, segment_name="w0").build(cols, tmp_path / "w0")
    controller.add_segment(table, "w0", {"location": str(tmp_path / "w0"),
                                         "numDocs": 4})
    brest = BrokerRestServer(broker)
    crest = ControllerRestServer(controller)
    yield brest, crest, controller, server
    brest.close()
    crest.close()
    server.stop()


def test_query_over_http(stack):
    brest = stack[0]
    conn = connect(brest.url)
    rs = conn.execute("SELECT path, SUM(hits) FROM web GROUP BY path ORDER BY path")
    assert rs.column_names[0] == "path"
    assert rs.rows == [["/a", 4.0], ["/b", 2.0], ["/c", 4.0]]
    assert rs.get(0, "path") == "/a"
    assert rs.execution_stats["numDocsScanned"] == 4


def test_query_error_surfaces(stack):
    brest = stack[0]
    conn = connect(brest.url)
    with pytest.raises(PinotClientError, match="not found"):
        conn.execute("SELECT * FROM nosuch")


def test_controller_rest_endpoints(stack, tmp_path):
    _, crest, controller, _ = stack

    def get(path):
        with urllib.request.urlopen(crest.url + path) as r:
            return json.loads(r.read())

    def post(path, body):
        req = urllib.request.Request(
            crest.url + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read())

    assert get("/health") == {"status": "OK"}
    assert "web_OFFLINE" in get("/tables")["tables"]
    assert get("/tables/web")["tableNameWithType"] == "web_OFFLINE"
    assert get("/schemas/web")["schemaName"] == "web"
    assert get("/segments/web")["segments"] == ["w0"]
    assert "Server_0" in get("/instances")["live"]

    # create a second table + push a segment over HTTP
    post("/schemas", Schema.build("t2", dimensions=[("x", "INT")]).to_json())
    post("/tables", {"tableName": "t2", "replication": 1})
    cols = {"x": np.arange(5, dtype=np.int32)}
    SegmentBuilder(Schema.build("t2", dimensions=[("x", "INT")]),
                   segment_name="t2_0").build(cols, tmp_path / "t2_0")
    out = post("/segments/t2/t2_0",
               {"location": str(tmp_path / "t2_0"), "numDocs": 5})
    assert out["assigned"] == ["Server_0"]

    req = urllib.request.Request(crest.url + "/tables/t2_OFFLINE",
                                 method="DELETE")
    with urllib.request.urlopen(req) as r:
        assert json.loads(r.read())["status"].startswith("table")


def test_http_404(stack):
    brest = stack[0]
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(brest.url + "/nope")
    assert e.value.code == 404


def test_quickstart_cli_once(capsys):
    from pinot_tpu.tools.admin import main

    rc = main(["quickstart", "--rows", "2000", "--servers", "1", "--once"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "SELECT COUNT(*) FROM baseballStats" in out
    assert "broker:" in out


def test_ingest_cli(tmp_path, capsys):
    from pinot_tpu.tools.admin import main

    (tmp_path / "in").mkdir()
    (tmp_path / "in" / "d.csv").write_text("path,hits\n/x,5\n/y,6\n")
    (tmp_path / "schema.json").write_text(json.dumps(SCHEMA.to_json()))
    (tmp_path / "job.yaml").write_text(f"""
inputDirURI: "{tmp_path / 'in'}"
outputDirURI: "{tmp_path / 'out'}"
recordReaderSpec:
  dataFormat: csv
""")
    rc = main(["ingest", "--spec", str(tmp_path / "job.yaml"),
               "--schema", str(tmp_path / "schema.json")])
    assert rc == 0
    assert "2 docs" in capsys.readouterr().out


def test_segment_parallel_scan(tmp_path):
    """connectors.scan_table: one arrow RecordBatch per segment through
    the broker's explicit-segment scatter plane (reference: Spark
    connector partitioned reads)."""
    import numpy as np
    import pytest

    pytest.importorskip("pyarrow")
    from pinot_tpu.cluster import (Broker, ClusterController, PropertyStore,
                                   ServerInstance)
    from pinot_tpu.connectors.dataframe import scan_table
    from pinot_tpu.segment.builder import SegmentBuilder
    from pinot_tpu.spi.data_types import Schema

    schema = Schema.build("scan", dimensions=[("k", "INT")],
                          metrics=[("v", "INT")])
    store = PropertyStore()
    controller = ClusterController(store)
    servers = [ServerInstance(store, f"S{i}", backend="host") for i in range(2)]
    for s in servers:
        s.start()
    broker = Broker(store)
    try:
        controller.add_schema(schema.to_json())
        controller.create_table({"tableName": "scan", "replication": 1})
        rng = np.random.default_rng(2)
        totals = {}
        for i in range(3):
            cols = {"k": rng.integers(0, 10, 1000).astype(np.int32),
                    "v": rng.integers(0, 100, 1000).astype(np.int32)}
            SegmentBuilder(schema, segment_name=f"sc{i}").build(
                cols, tmp_path / f"sc{i}")
            controller.add_segment("scan_OFFLINE", f"sc{i}",
                                   {"location": str(tmp_path / f"sc{i}"),
                                    "numDocs": 1000})
            totals[f"sc{i}"] = int(cols["v"][cols["k"] > 4].sum())
        batches = dict(scan_table(broker, "scan_OFFLINE", ["k", "v"],
                                  num_readers=3, where="k > 4"))
        assert set(batches) == {"sc0", "sc1", "sc2"}
        for seg, batch in batches.items():
            assert sum(batch.column("v").to_pylist()) == totals[seg]
    finally:
        for s in servers:
            s.stop()


def test_rest_rebalance_and_instance_partitions(tmp_path):
    """REST surface for instance partitions, rebalance status, tier
    relocation (reference: controller resources under /tables/...)."""
    import json
    import urllib.request

    import numpy as np

    from pinot_tpu.cluster import ClusterController, PropertyStore, ServerInstance
    from pinot_tpu.cluster.rest import ControllerRestServer
    from pinot_tpu.segment.builder import SegmentBuilder
    from pinot_tpu.spi.data_types import Schema

    schema = Schema.build("rst", dimensions=[("k", "INT")], metrics=[("v", "INT")])
    store = PropertyStore()
    controller = ClusterController(store)
    servers = [ServerInstance(store, f"S{i}", backend="host") for i in range(4)]
    for s in servers:
        s.start()
    rest = ControllerRestServer(controller)
    try:
        def call(method, path, body=None):
            req = urllib.request.Request(
                rest.url + path, method=method,
                data=json.dumps(body).encode() if body is not None else None,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as r:
                return json.loads(r.read())

        call("POST", "/schemas", schema.to_json())
        call("POST", "/tables", {"tableName": "rst", "replication": 2})
        rng = np.random.default_rng(0)
        for i in range(4):
            cols = {"k": rng.integers(0, 5, 100).astype(np.int32),
                    "v": rng.integers(0, 9, 100).astype(np.int32)}
            SegmentBuilder(schema, segment_name=f"r{i}").build(cols, tmp_path / f"r{i}")
            call("POST", f"/segments/rst/r{i}",
                 {"location": str(tmp_path / f"r{i}"), "numDocs": 100})

        ip = call("POST", "/tables/rst/instancePartitions",
                  {"numReplicaGroups": 2})
        assert len(ip["replicaGroups"]) == 2
        assert call("GET", "/tables/rst/instancePartitions") == ip

        res = call("POST", "/tables/rst/rebalance")
        assert res["status"] == "DONE"
        st = call("GET", "/tables/rst/rebalanceStatus")
        assert st["status"] == "DONE"

        rel = call("POST", "/tables/rst/relocate")
        assert rel["status"] == "DONE" and rel["moves"] == 0  # no tiers
    finally:
        rest.close()
        for s in servers:
            s.stop()


def test_server_rest_endpoints(stack):
    """Server-role admin/debug REST (reference: pinot-server api/resources)."""
    from pinot_tpu.cluster.rest import ServerRestServer

    server = stack[3]
    rest = ServerRestServer(server)
    try:
        def get(path, expect=200):
            try:
                with urllib.request.urlopen(rest.url + path) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                assert e.code == expect, (path, e.code)
                return e.code, json.loads(e.read())

        st, h = get("/health")
        assert st == 200 and h["status"] == "OK"
        st, inst = get("/instance")
        assert inst["instanceId"] == "Server_0"
        st, tables = get("/tables")
        assert "web_OFFLINE" in tables["tables"]
        st, segs = get("/tables/web_OFFLINE/segments")
        assert segs["segments"][0]["name"] == "w0"
        assert segs["segments"][0]["numDocs"] == 4
        st, size = get("/tables/web_OFFLINE/size")
        assert size["totalDiskSizeBytes"] > 0
        st, meta = get("/segments/web_OFFLINE/w0/metadata")
        assert meta["numDocs"] == 4
        assert meta["columns"]["path"]["cardinality"] == 3
        st, dbg = get("/debug/tables/web_OFFLINE")
        assert dbg["hostedSegments"] == ["w0"]
        assert dbg["missing"] == []
        st, q = get("/debug/queries")
        assert q["inflight"] == []
        st, _ = get("/tables/nosuch/segments", expect=404)
        assert st == 404
        # liveness vs readiness split
        st, _ = get("/health/liveness")
        assert st == 200
        server._started = False
        st, r = get("/health/readiness", expect=503)
        assert st == 503 and r["status"] == "STARTING"
        server._started = True
    finally:
        rest.close()
