"""Multi-tier result cache (ISSUE 5): fingerprints, partial reuse,
broker cache, lineage invalidation.

Tier 1 — cache/keys.py: process-stable program fingerprints (two fresh
planners → byte-identical keys; any literal change → different keys; no
repr()/id() fallback by construction).

Tier 2 — cache/partial.py + device-resident tabs: a warm repeat of a
multi-segment query must return bit-identical rows with ZERO device
dispatches, respect its byte budget, survive in-place combine mutation,
and never serve a replaced segment's stale partial (crc in the key).

Tier 3 — cache/results.py + broker wiring: full-response reuse keyed on
(query_fp, lineage epoch); segment replace and realtime commit bump the
epoch and the post-replace answer matches a cold broker bit-for-bit.
"""

from __future__ import annotations

from types import SimpleNamespace

import numpy as np
import pytest

from pinot_tpu.cache.keys import (UnfingerprintableError, canonical_bytes,
                                  program_fingerprint, query_fingerprint,
                                  segment_token)
from pinot_tpu.cache.partial import GLOBAL_PARTIAL_CACHE, SegmentPartialCache
from pinot_tpu.cache.results import (BrokerResultCache, bump_lineage_epoch,
                                     lineage_epoch)
from pinot_tpu.engine.query_executor import QueryExecutor
from pinot_tpu.query.parser.sql import parse_sql
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.segment.device_cache import GLOBAL_DEVICE_CACHE, DeviceSegmentCache
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.spi.data_types import Schema

SCHEMA = Schema.build(
    "rc",
    dimensions=[("rck", "INT"), ("rcd", "INT")],
    metrics=[("rcv", "LONG")])

DENSE_SQL = ("SELECT rck, COUNT(*), SUM(rcv), AVG(rcv) FROM rc "
             "GROUP BY rck ORDER BY rck LIMIT 1000")
AGG_SQL = "SELECT COUNT(*), SUM(rcv), MIN(rcv), MAX(rcv) FROM rc"
SPARSE_SQL = ("SET sparseGroupBy = true; "
              "SELECT rck, COUNT(*), SUM(rcv) FROM rc "
              "GROUP BY rck ORDER BY rck LIMIT 100000")


@pytest.fixture(autouse=True)
def _fresh_caches(monkeypatch):
    """Every test starts from cold process-global caches with the segment
    cache at its default-on state, regardless of what other modules set."""
    monkeypatch.setenv("PINOT_TPU_SEGMENT_CACHE", "1")
    monkeypatch.setenv("PINOT_TPU_RESULT_CACHE", "1")
    GLOBAL_PARTIAL_CACHE.clear()
    GLOBAL_DEVICE_CACHE.drop_partials()
    yield
    GLOBAL_PARTIAL_CACHE.clear()
    GLOBAL_DEVICE_CACHE.drop_partials()


def _gen(rng, n=3000):
    return {"rck": rng.integers(0, 32, n).astype(np.int32),
            "rcd": rng.integers(0, 12, n).astype(np.int32),
            "rcv": rng.integers(-200, 200, n).astype(np.int64)}


@pytest.fixture(scope="module")
def engine(tmp_path_factory):
    rng = np.random.default_rng(53)
    d = tmp_path_factory.mktemp("rc_segs")
    dirs = []
    segs = []
    for i in range(4):
        path = d / f"rc{i}"
        SegmentBuilder(SCHEMA, segment_name=f"rc{i}").build(_gen(rng), path)
        dirs.append(path)
        segs.append(load_segment(path))
    qe = QueryExecutor(backend="tpu")
    qe.add_table(SCHEMA, segs)
    return SimpleNamespace(qe=qe, dirs=dirs)


def _rows(resp):
    assert not resp.exceptions, resp.exceptions
    return resp.result_table.rows


# -- tier 1: fingerprints -----------------------------------------------------


def test_program_fingerprint_stable_across_fresh_planners(engine):
    """Same SQL parsed twice, planned by two independent executors over two
    independent loads of the same segment → byte-identical program_fp."""
    q1, q2 = parse_sql(DENSE_SQL), parse_sql(DENSE_SQL)
    s1, s2 = load_segment(engine.dirs[0]), load_segment(engine.dirs[0])
    e1, e2 = QueryExecutor(backend="tpu"), QueryExecutor(backend="tpu")
    fp1 = program_fingerprint(e1.tpu.plan(q1, s1), q1)
    fp2 = program_fingerprint(e2.tpu.plan(q2, s2), q2)
    assert fp1 is not None
    assert fp1 == fp2
    assert segment_token(s1) == segment_token(s2)
    assert query_fingerprint(q1) == query_fingerprint(q2)


def test_literal_change_changes_fingerprint(engine):
    seg = load_segment(engine.dirs[0])
    e = QueryExecutor(backend="tpu")
    sql_a = "SELECT SUM(rcv) FROM rc WHERE rck > 4"
    sql_b = "SELECT SUM(rcv) FROM rc WHERE rck > 3"
    qa, qb = parse_sql(sql_a), parse_sql(sql_b)
    fpa = program_fingerprint(e.tpu.plan(qa, seg), qa)
    fpb = program_fingerprint(e.tpu.plan(qb, seg), qb)
    assert fpa is not None and fpb is not None
    assert fpa != fpb
    assert query_fingerprint(qa) != query_fingerprint(qb)


def test_canonical_encoder_is_closed_world():
    # value-equal containers encode identically regardless of construction
    assert canonical_bytes({"a": 1, "b": 2}) == canonical_bytes({"b": 2, "a": 1})
    assert canonical_bytes((1, "x", 2.5)) == canonical_bytes([1, "x", 2.5])
    assert canonical_bytes(np.int32(7)) == canonical_bytes(np.asarray(7, np.int32))
    # type tags keep equal payloads of different types apart
    assert canonical_bytes(1) != canonical_bytes(1.0)
    assert canonical_bytes(True) != canonical_bytes(1)
    # NO repr()/id() fallback: an arbitrary object raises instead of
    # silently leaking a memory address into the key
    with pytest.raises(UnfingerprintableError):
        canonical_bytes(object())

    class Opaque:
        pass

    with pytest.raises(UnfingerprintableError):
        canonical_bytes({"k": Opaque()})
    # ... and a plan carrying one yields None → callers bypass the cache
    fake = SimpleNamespace(program=Opaque(), slots=(), fused_ok=True, params=())
    assert program_fingerprint(fake, parse_sql("SELECT COUNT(*) FROM rc")) is None


# -- tier 2: segment partial reuse (the zero-dispatch warm repeat) ------------


@pytest.mark.parametrize("sql", [DENSE_SQL, AGG_SQL], ids=["groupby", "agg"])
def test_warm_repeat_is_zero_dispatch_bit_identical(engine, sql):
    cold = engine.qe.execute_sql(sql)
    assert cold.num_segments_cache_miss == 4
    assert cold.num_device_dispatches > 0
    warm = engine.qe.execute_sql(sql)
    assert _rows(warm) == _rows(cold)
    assert warm.num_segments_cache_hit == 4
    assert warm.num_device_dispatches == 0
    j = warm.to_json()
    assert j["numSegmentsCacheHit"] == 4
    assert j.get("numDeviceDispatches", 0) == 0


def test_sparse_warm_repeat_is_zero_dispatch(engine):
    cold = engine.qe.execute_sql(SPARSE_SQL)
    assert cold.num_device_dispatches > 0
    warm = engine.qe.execute_sql(SPARSE_SQL)
    assert _rows(warm) == _rows(cold)
    assert warm.num_segments_cache_hit == 4
    assert warm.num_device_dispatches == 0
    # the device-resident per-segment tabs are their own tier: with the
    # host cache wiped, warm overlap still skips every program dispatch
    GLOBAL_PARTIAL_CACHE.clear()
    tab_warm = engine.qe.execute_sql(SPARSE_SQL)
    assert _rows(tab_warm) == _rows(cold)
    assert tab_warm.num_device_dispatches == 0
    assert GLOBAL_DEVICE_CACHE.hbm_stats()["hbmPartialEntries"] >= 4


def test_cross_executor_warm_reuse(engine):
    """A second executor with its own planner over its own segment loads
    hits the first executor's partials — keys are content-addressed, never
    object identity."""
    cold = engine.qe.execute_sql(DENSE_SQL)
    qe2 = QueryExecutor(backend="tpu")
    qe2.add_table(SCHEMA, [load_segment(d) for d in engine.dirs])
    warm = qe2.execute_sql(DENSE_SQL)
    assert _rows(warm) == _rows(cold)
    assert warm.num_segments_cache_hit == 4
    assert warm.num_device_dispatches == 0


def test_segment_cache_opt_out(engine):
    off = "SET segmentCache = false; "
    engine.qe.execute_sql(off + DENSE_SQL)
    again = engine.qe.execute_sql(off + DENSE_SQL)
    assert not again.exceptions
    assert again.num_segments_cache_hit == 0
    assert again.num_segments_cache_miss == 0
    assert again.num_device_dispatches > 0


def test_triple_run_mutation_safety(engine):
    """combine merges agg states IN PLACE — three identical runs must stay
    bit-identical (the cache deep-copies on put AND get)."""
    sql = ("SELECT rck, DISTINCTCOUNT(rcd), AVG(rcv) FROM rc "
           "GROUP BY rck ORDER BY rck LIMIT 1000")
    first = _rows(engine.qe.execute_sql(sql))
    for _ in range(2):
        assert _rows(engine.qe.execute_sql(sql)) == first


def test_replaced_segment_same_name_never_serves_stale(engine, tmp_path):
    """A segment re-pushed under the SAME name with different content gets
    a different crc → different key → recomputed, even before any eager
    invalidation runs."""
    rng = np.random.default_rng(99)
    old_dir, new_dir = tmp_path / "va", tmp_path / "vb"
    SegmentBuilder(SCHEMA, segment_name="swap0").build(_gen(rng), old_dir)
    SegmentBuilder(SCHEMA, segment_name="swap0").build(_gen(rng), new_dir)
    sql = "SELECT COUNT(*), SUM(rcv) FROM rc"
    qe_old = QueryExecutor(backend="tpu")
    qe_old.add_table(SCHEMA, [load_segment(old_dir)])
    rows_old = _rows(qe_old.execute_sql(sql))
    qe_new = QueryExecutor(backend="tpu")
    qe_new.add_table(SCHEMA, [load_segment(new_dir)])
    resp_new = qe_new.execute_sql(sql)
    assert resp_new.num_segments_cache_hit == 0
    assert _rows(resp_new) != rows_old  # different content, fresh answer


def test_partial_cache_eviction_respects_budget():
    c = SegmentPartialCache(max_bytes=600)  # opaque entries estimate 256B
    c.put(("k1",), ["p1"], ("s1",))
    c.put(("k2",), ["p2"], ("s2",))
    c.put(("k3",), ["p3"], ("s3",))  # over budget → LRU k1 evicted
    assert c.get(("k1",)) is None
    assert c.get(("k2",)) == ["p2"]
    assert c.get(("k3",)) == ["p3"]
    st = c.stats()
    assert st["entries"] == 2 and st["evictions"] == 1
    assert st["bytes"] <= c.max_bytes
    assert c.invalidate_segment("s2") == 1
    assert c.get(("k2",)) is None
    # a value alone over budget is skipped, not inserted-then-thrashed
    tiny = SegmentPartialCache(max_bytes=100)
    tiny.put(("big",), ["x"], ("s",))
    assert tiny.stats()["entries"] == 0


def test_device_partial_budget_evicts_partials_first():
    cache = DeviceSegmentCache(budget_bytes=2000)
    a = np.zeros(150, np.int64)  # 1200 bytes
    cache.put_partial(("k1",), (a,), "segA")
    cache.put_partial(("k2",), (np.zeros(150, np.int64),), "segB")
    # 2400 > 2000: the oldest partial goes; the fresh insert survives
    assert cache.get_partial(("k1",)) is None
    assert cache.get_partial(("k2",)) is not None
    st = cache.hbm_stats()
    assert st["hbmPartialEntries"] == 1
    assert st["hbmPartialBytes"] == 1200
    # oversized partial is refused outright
    cache.put_partial(("big",), (np.zeros(1000, np.int64),), "segC")
    assert cache.get_partial(("big",)) is None
    # lineage drop by segment name
    assert cache.drop_partials(segment_name="segB") == 1
    assert cache.hbm_stats()["hbmPartialEntries"] == 0
    # OOM relief sheds partials
    cache.put_partial(("k3",), (np.zeros(8, np.int64),), "segD")
    cache.evict_all_except(None)
    assert cache.hbm_stats()["hbmPartialEntries"] == 0


# -- tier 3: broker result cache + lineage epochs -----------------------------


def test_broker_result_cache_ttl_and_capacity():
    clk = [0.0]
    c = BrokerResultCache(max_bytes=10_000, ttl_s=10.0, clock=lambda: clk[0])
    resp = SimpleNamespace(result_table=None)
    c.put(("k",), resp)
    assert c.get(("k",)) is not None
    clk[0] = 9.0
    assert c.get(("k",)) is not None
    clk[0] = 10.5  # past TTL: expired on read, counted as a miss
    assert c.get(("k",)) is None
    assert c.stats()["entries"] == 0 and c.stats()["misses"] == 1

    cap = BrokerResultCache(max_bytes=1200, ttl_s=1e9, clock=lambda: clk[0])
    for i in range(3):  # 512B each → third insert evicts the LRU first
        cap.put((f"k{i}",), SimpleNamespace(result_table=None))
    assert cap.get(("k0",)) is None
    assert cap.get(("k2",)) is not None
    st = cap.stats()
    assert st["evictions"] == 1 and st["bytes"] <= 1200
    assert cap.clear() == 2


def test_lineage_epoch_helpers():
    from pinot_tpu.cluster import PropertyStore

    store = PropertyStore()
    assert lineage_epoch(store, "t_OFFLINE") == 0
    bump_lineage_epoch(store, "t_OFFLINE")
    bump_lineage_epoch(store, "t_OFFLINE")
    assert lineage_epoch(store, "t_OFFLINE") == 2
    assert lineage_epoch(store, "t_REALTIME") == 0


@pytest.fixture()
def cluster(tmp_path):
    from pinot_tpu.cluster import (Broker, ClusterController, PropertyStore,
                                   ServerInstance)

    pschema = Schema.build("p", dimensions=[("k", "INT")],
                           metrics=[("v", "INT")])
    store = PropertyStore()
    controller = ClusterController(store)
    server = ServerInstance(store, "Server_0", backend="host")
    server.start()
    broker = Broker(store)
    controller.add_schema(pschema.to_json())

    def seg(name, vals):
        cols = {"k": np.arange(len(vals), dtype=np.int32),
                "v": np.asarray(vals, dtype=np.int32)}
        SegmentBuilder(pschema, segment_name=name).build(cols, tmp_path / name)
        return str(tmp_path / name)

    yield SimpleNamespace(store=store, controller=controller, server=server,
                          broker=broker, seg=seg)
    server.stop()


def test_broker_cache_hit_and_replace_invalidation(cluster):
    """Warm repeat hits; a lineage replace (the minion merge/refresh path)
    bumps the epoch and the next answer matches a cold broker bit-for-bit."""
    from pinot_tpu.cluster import Broker
    from pinot_tpu.cluster.periodic import SegmentLineageManager

    table = cluster.controller.create_table(
        {"tableName": "p", "replication": 1})
    cluster.controller.add_segment(table, "old0", {
        "location": cluster.seg("old0", [1, 2]), "numDocs": 2})
    assert lineage_epoch(cluster.store, table) >= 1  # upload bumped it
    sql = "SELECT COUNT(*), SUM(v) FROM p"
    r1 = cluster.broker.execute_sql(sql)
    assert _rows(r1) == [[2, 3.0]]
    assert r1.cache_outcome == "miss"
    r2 = cluster.broker.execute_sql(sql)
    assert r2.cache_outcome == "hit"
    assert _rows(r2) == _rows(r1)
    assert cluster.broker.result_cache.stats()["hits"] == 1

    lineage = SegmentLineageManager(cluster.store, cluster.controller)
    lid = lineage.start_replace(table, ["old0"], ["m0"])
    cluster.controller.add_segment(table, "m0", {
        "location": cluster.seg("m0", [10, 20]), "numDocs": 2})
    epoch_before = lineage_epoch(cluster.store, table)
    lineage.end_replace(table, lid)
    assert lineage_epoch(cluster.store, table) > epoch_before
    r3 = cluster.broker.execute_sql(sql)
    assert r3.cache_outcome == "miss"  # old key unreachable, recomputed
    cold = Broker(cluster.store).execute_sql(sql)
    assert _rows(r3) == _rows(cold) == [[2, 30.0]]


def test_lineage_revert_bumps_epoch(cluster):
    from pinot_tpu.cluster.periodic import SegmentLineageManager

    table = cluster.controller.create_table(
        {"tableName": "p", "replication": 1})
    cluster.controller.add_segment(table, "keep", {
        "location": cluster.seg("keep", [7]), "numDocs": 1})
    lineage = SegmentLineageManager(cluster.store, cluster.controller)
    lid = lineage.start_replace(table, ["keep"], ["bad"])
    before = lineage_epoch(cluster.store, table)
    lineage.revert_replace(table, lid)
    assert lineage_epoch(cluster.store, table) > before


def test_realtime_commit_bumps_epoch():
    from pinot_tpu.cluster import PropertyStore
    from pinot_tpu.realtime.completion import (COMMIT, COMMIT_SUCCESS,
                                               SegmentCompletionManager)

    store = PropertyStore()
    mgr = SegmentCompletionManager(store, num_replicas=1)
    t = "p_REALTIME"
    assert lineage_epoch(store, t) == 0
    assert mgr.segment_consumed(t, "p__0", "i1", 100).status == COMMIT
    mgr.segment_commit_start(t, "p__0", "i1", 100)
    out = mgr.segment_commit_end(t, "p__0", "i1", 100, "/deep/p__0")
    assert out.status == COMMIT_SUCCESS
    assert lineage_epoch(store, t) == 1


def test_realtime_table_bypasses_result_cache(cluster):
    """A REALTIME half means consuming rows advance without lineage events
    — the broker must never cache such a table's answers."""
    cluster.controller.create_table(
        {"tableName": "p", "tableType": "OFFLINE", "replication": 1})
    cluster.controller.create_table(
        {"tableName": "p", "tableType": "REALTIME", "replication": 1,
         "streamConfigs": {}})
    off = cluster.controller.add_segment(
        "p_OFFLINE", "o0", {"location": cluster.seg("o0", [5]), "numDocs": 1})
    assert off
    sql = "SELECT SUM(v) FROM p"
    r1 = cluster.broker.execute_sql(sql)
    r2 = cluster.broker.execute_sql(sql)
    assert r1.cache_outcome == "bypass" and r2.cache_outcome == "bypass"
    assert cluster.broker.result_cache.stats()["entries"] == 0


def test_result_cache_opt_outs(cluster):
    table = cluster.controller.create_table(
        {"tableName": "p", "replication": 1})
    cluster.controller.add_segment(table, "s0", {
        "location": cluster.seg("s0", [1]), "numDocs": 1})
    for sql in ("SET resultCache = false; SELECT SUM(v) FROM p",
                "SET trace = true; SELECT SUM(v) FROM p"):
        r = cluster.broker.execute_sql(sql)
        assert not r.exceptions, r.exceptions
        assert r.cache_outcome == "bypass"
    assert cluster.broker.result_cache.stats()["entries"] == 0
    # non-deterministic SQL bypasses at the key level (decision tree)
    try:
        q = parse_sql("SELECT SUM(v) FROM p WHERE v < NOW()")
    except Exception:
        q = None  # grammar rejects NOW(): nothing to cache either way
    if q is not None and "now(" in str(q).lower():
        assert cluster.broker._result_cache_key(q, None) is None


def test_debug_cache_and_delete_cache_endpoints(cluster):
    import json
    import urllib.request

    from pinot_tpu.cluster.rest import BrokerRestServer

    table = cluster.controller.create_table(
        {"tableName": "p", "replication": 1})
    cluster.controller.add_segment(table, "s0", {
        "location": cluster.seg("s0", [1, 2, 3]), "numDocs": 3})
    brest = BrokerRestServer(cluster.broker)
    try:
        for _ in range(2):
            r = cluster.broker.execute_sql("SELECT SUM(v) FROM p")
            assert not r.exceptions
        with urllib.request.urlopen(brest.url + "/debug/cache") as resp:
            dbg = json.loads(resp.read())
        assert dbg["resultCache"]["entries"] == 1
        assert dbg["resultCache"]["hits"] == 1
        assert "segmentPartialCache" in dbg
        assert "hbmPartialEntries" in dbg["devicePartials"]
        req = urllib.request.Request(brest.url + "/cache", method="DELETE")
        with urllib.request.urlopen(req) as resp:
            out = json.loads(resp.read())
        assert out["resultEntriesDropped"] == 1
        with urllib.request.urlopen(brest.url + "/debug/cache") as resp:
            dbg = json.loads(resp.read())
        assert dbg["resultCache"]["entries"] == 0
    finally:
        brest.close()


def test_querylog_tags_cache_outcome():
    from pinot_tpu.cluster.querylog import QueryLogger

    ql = QueryLogger(slow_threshold_ms=0.0)
    hit = SimpleNamespace(time_used_ms=5.0, cache_outcome="hit")
    plain = SimpleNamespace(time_used_ms=5.0)
    ql.log("SELECT 1", hit, table="p")
    ql.log("SELECT 2", plain, table="p")
    entries = {e["sql"]: e for e in ql.slow_queries()}
    assert entries["SELECT 1"]["cacheOutcome"] == "hit"
    assert "cacheOutcome" not in entries["SELECT 2"]
