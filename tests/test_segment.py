"""Segment build → load round-trip tests.

Mirrors the reference's writer→reader round-trip strategy per index type
(pinot-segment-local/src/test — SURVEY.md §4.1).
"""

import numpy as np
import pytest

from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.spi.data_types import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table_config import IndexingConfig, TableConfig


@pytest.fixture
def schema():
    return Schema.build(
        "testTable",
        dimensions=[("teamID", "STRING"), ("league", "STRING"), ("year", "INT")],
        metrics=[("runs", "INT"), ("salary", "DOUBLE")],
        date_times=[("ts", "TIMESTAMP")],
    )


def make_rows(n, rng):
    teams = ["BOS", "NYA", "CHA", "SFN", "LAN", "ATL"]
    leagues = ["AL", "NL"]
    return [
        {
            "teamID": teams[int(rng.integers(len(teams)))],
            "league": leagues[int(rng.integers(2))],
            "year": int(rng.integers(1900, 2024)),
            "runs": int(rng.integers(0, 150)),
            "salary": float(rng.random() * 1e6),
            "ts": int(rng.integers(1_500_000_000_000, 1_700_000_000_000)),
        }
        for _ in range(n)
    ]


def test_build_load_roundtrip(tmp_path, schema, rng):
    rows = make_rows(500, rng)
    builder = SegmentBuilder(schema, segment_name="seg_0")
    builder.build_from_rows(rows, tmp_path / "seg_0")

    seg = load_segment(tmp_path / "seg_0")
    assert seg.num_docs == 500
    assert seg.name == "seg_0"
    assert set(seg.columns()) == {"teamID", "league", "year", "runs", "salary", "ts"}

    for col, key in [("teamID", "teamID"), ("year", "year"), ("runs", "runs"), ("salary", "salary")]:
        got = seg.get_values(col)
        want = np.asarray([r[key] for r in rows])
        if got.dtype == object:
            assert list(got) == list(want)
        else:
            np.testing.assert_allclose(got.astype(np.float64), want.astype(np.float64))


def test_dictionary_sorted_and_metadata(tmp_path, schema, rng):
    rows = make_rows(200, rng)
    SegmentBuilder(schema, segment_name="s").build_from_rows(rows, tmp_path / "s")
    seg = load_segment(tmp_path / "s")

    d = seg.get_dictionary("teamID")
    assert list(d.values) == sorted(d.values)
    m = seg.column_metadata("year")
    years = [r["year"] for r in rows]
    assert int(m.min_value) == min(years)
    assert int(m.max_value) == max(years)
    assert m.cardinality == len(set(years))
    assert m.bits_per_value >= 1
    # dict ids decode to within cardinality
    ids = seg.get_dict_ids("year")
    assert ids.min() >= 0 and ids.max() < m.cardinality


def test_raw_column(tmp_path, rng):
    schema = Schema.build("t", dimensions=[("d", "INT")], metrics=[("m", "DOUBLE")])
    cfg = TableConfig(table_name="t", indexing=IndexingConfig(no_dictionary_columns=["m"]))
    vals = rng.random(100)
    cols = {"d": list(range(100)), "m": list(vals)}
    SegmentBuilder(schema, cfg, "s").build(cols, tmp_path / "s")
    seg = load_segment(tmp_path / "s")
    assert seg.column_metadata("m").encoding == "RAW"
    np.testing.assert_allclose(seg.get_raw("m"), vals)
    assert seg.column_metadata("d").is_sorted


def test_nulls(tmp_path):
    schema = Schema.build("t", dimensions=[("d", "STRING")], metrics=[("m", "INT")])
    cols = {"d": ["a", None, "b", None], "m": [1, 2, None, 4]}
    SegmentBuilder(schema, segment_name="s").build(cols, tmp_path / "s")
    seg = load_segment(tmp_path / "s")
    np.testing.assert_array_equal(seg.get_null_bitmap("d"), [False, True, False, True])
    np.testing.assert_array_equal(seg.get_null_bitmap("m"), [False, False, True, False])
    # defaults: dimension string -> "null", metric int -> 0
    assert list(seg.get_values("d")) == ["a", "null", "b", "null"]
    np.testing.assert_array_equal(seg.get_values("m"), [1, 2, 0, 4])


def test_mv_column(tmp_path):
    schema = Schema("t")
    schema.add_field(FieldSpec("tags", DataType.STRING, FieldType.DIMENSION, single_value=False))
    schema.add_field(FieldSpec("m", DataType.INT, FieldType.METRIC))
    cols = {"tags": [["x", "y"], ["y"], [], ["z", "x", "y"]], "m": [1, 2, 3, 4]}
    SegmentBuilder(schema, segment_name="s").build(cols, tmp_path / "s")
    seg = load_segment(tmp_path / "s")
    m = seg.column_metadata("tags")
    assert not m.single_value
    assert m.max_number_of_multi_values == 3
    mv = seg.get_mv_values("tags")
    assert list(mv[0]) == ["x", "y"]
    assert list(mv[1]) == ["y"]
    assert list(mv[2]) == []
    assert list(mv[3]) == ["z", "x", "y"]
    mat = seg.get_mv_dict_id_matrix("tags")
    assert mat.shape == (4, 3)
    # pad slots carry the sentinel id == cardinality
    assert mat[1, 1] == m.cardinality and mat[1, 2] == m.cardinality


def test_time_column_range(tmp_path, schema, rng):
    rows = make_rows(50, rng)
    cfg = TableConfig(table_name="t")
    cfg.validation.time_column_name = "ts"
    SegmentBuilder(schema, cfg, "s").build_from_rows(rows, tmp_path / "s")
    seg = load_segment(tmp_path / "s")
    ts = [r["ts"] for r in rows]
    assert seg.metadata.start_time == min(ts)
    assert seg.metadata.end_time == max(ts)


def test_backfill_indexes_on_load(tmp_path, schema, rng):
    """Indexes added to the table config AFTER a segment was built are
    backfilled at load (reference: SegmentPreProcessor on-load backfill)."""
    rows = make_rows(300, rng)
    SegmentBuilder(schema, segment_name="bf").build_from_rows(rows, tmp_path / "bf")
    seg = load_segment(tmp_path / "bf")
    assert seg.get_inverted_index("teamID") is None
    assert seg.get_bloom_filter("league") is None

    cfg = IndexingConfig(inverted_index_columns=["teamID"],
                         bloom_filter_columns=["league"])
    built = seg.backfill_indexes(cfg)
    assert set(built) == {"inverted:teamID", "bloom:league"}
    inv = seg.get_inverted_index("teamID")
    assert inv is not None
    # the backfilled inverted index agrees with the forward index
    ids = seg.get_dict_ids("teamID")
    import numpy as np

    for dict_id in range(seg.column_metadata("teamID").cardinality):
        np.testing.assert_array_equal(
            inv.postings(dict_id), np.nonzero(ids == dict_id)[0])
    bloom = seg.get_bloom_filter("league")
    assert bloom is not None
    assert bloom.might_contain("AL") or bloom.might_contain("NL")
    # idempotent: a second call builds nothing
    assert seg.backfill_indexes(cfg) == []
