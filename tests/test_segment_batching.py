"""Stacked segment batching (ISSUE 3): bit-parity oracle + structure guards.

Two families of checks:

  * PARITY — every cell of {COUNT, SUM, MIN, MAX, DISTINCTCOUNT, AVG} ×
    {filter, no filter} over MIXED segment sizes spanning a pad-bucket
    boundary (6000/9000/3000 rows straddle the 8192 bucket) must return
    rows bit-for-bit equal to `SET segmentBatch = false` (per-segment
    dispatch). Sparse group-by + device combine and plain selections ride
    the same oracle.

  * STRUCTURE — a multi-segment single-family query must execute with
    exactly ONE device dispatch (was S), the compile guard must record one
    family key (not S per-segment keys), mixed pad buckets must split into
    exactly the predicted number of families, and EXPLAIN IMPLEMENTATION
    must surface the SEGMENT_BATCH row.
"""

from __future__ import annotations

import numpy as np
import pytest

from pinot_tpu.engine import executor as executor_mod
from pinot_tpu.engine.query_executor import QueryExecutor
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.spi.data_types import Schema

SCHEMA = Schema.build(
    "sb",
    dimensions=[("k", "INT"), ("d", "INT")],
    metrics=[("v", "LONG"), ("f", "DOUBLE")])

N_KEYS = 40
# 6000/3000 pad to the 8192 bucket, 9000 pads to 16384 — the fixture
# deliberately straddles a bucket boundary so batching must mix stacked
# and differently-shaped segments in one query
MIXED_SIZES = [6000, 9000, 3000]

NO_BATCH = "SET segmentBatch = false; "


@pytest.fixture(autouse=True)
def _no_segment_cache(monkeypatch):
    # the segment partial-result cache (cache/partial.py) would satisfy
    # repeat queries with zero dispatches — and since segmentBatch is an
    # execution-only option, the NO_BATCH "solo" runs share the batched
    # runs' fingerprints and would hit their cached partials, turning every
    # parity oracle and dispatch-count guard here into a self-comparison.
    # This module tests the dispatcher, so caching is off throughout.
    monkeypatch.setenv("PINOT_TPU_SEGMENT_CACHE", "0")


def _gen(rng, n):
    return {
        "k": rng.integers(0, N_KEYS, n).astype(np.int32),
        "d": rng.integers(0, 16, n).astype(np.int32),
        # 1000 possible values keeps every segment's v-dictionary inside
        # the 1024 pad bucket regardless of segment size
        "v": rng.integers(-500, 500, n).astype(np.int64),
        "f": rng.normal(100.0, 25.0, n).astype(np.float64),
    }


@pytest.fixture(scope="module")
def mixed(tmp_path_factory):
    rng = np.random.default_rng(31)
    d = tmp_path_factory.mktemp("sb_mixed")
    segs = []
    for i, n in enumerate(MIXED_SIZES):
        SegmentBuilder(SCHEMA, segment_name=f"m{i}").build(
            _gen(rng, n), d / f"m{i}")
        segs.append(load_segment(d / f"m{i}"))
    qe = QueryExecutor(backend="tpu")
    qe.add_table(SCHEMA, segs)
    return qe


@pytest.fixture(scope="module")
def uniform(tmp_path_factory):
    """Four segments built from IDENTICAL rows: metadata (and therefore the
    batch family key) is equal by construction — one family, guaranteed."""
    rng = np.random.default_rng(77)
    cols = _gen(rng, 2048)
    d = tmp_path_factory.mktemp("sb_uniform")
    segs = []
    for i in range(4):
        SegmentBuilder(SCHEMA, segment_name=f"u{i}").build(cols, d / f"u{i}")
        segs.append(load_segment(d / f"u{i}"))
    qe = QueryExecutor(backend="tpu")
    qe.add_table(SCHEMA, segs)
    return qe


def _rows(resp):
    assert not resp.exceptions, resp.exceptions
    return resp.result_table.rows


def _assert_parity(qe, sql):
    batched = qe.execute_sql(sql)
    solo = qe.execute_sql(NO_BATCH + sql)
    # bit-for-bit: no tolerance, floats included — the batched kernel is a
    # vmap of the exact per-segment impl and combines in segment order
    assert _rows(batched) == _rows(solo), sql
    assert batched.num_docs_scanned == solo.num_docs_scanned
    return batched, solo


MATRIX_SQL = ("SELECT k, COUNT(*), SUM(v), MIN(v), MAX(v), "
              "DISTINCTCOUNT(d), AVG(v) FROM sb {where}"
              "GROUP BY k ORDER BY k LIMIT 100000")


@pytest.mark.parametrize("where", ["", "WHERE v > 100 AND d < 12 "],
                         ids=["nofilter", "filter"])
def test_groupby_matrix_parity(mixed, where):
    _assert_parity(mixed, MATRIX_SQL.format(where=where))


@pytest.mark.parametrize("where", ["", "WHERE v > 100 AND d < 12 "],
                         ids=["nofilter", "filter"])
def test_aggregation_only_parity(mixed, where):
    _assert_parity(
        mixed, "SELECT COUNT(*), SUM(v), MIN(v), MAX(v), "
               f"DISTINCTCOUNT(d), AVG(f), SUM(f) FROM sb {where}")


def test_sparse_groupby_device_combine_parity(mixed):
    # the batched RAW dispatch must feed the device-side sparse combine the
    # same per-segment tables, in the same merge order, as solo dispatch
    for where in ("", "WHERE v > 100 "):
        _assert_parity(
            mixed, "SET sparseGroupBy = true; "
                   "SELECT k, COUNT(*), SUM(v), DISTINCTCOUNT(d) FROM sb "
                   f"{where}GROUP BY k ORDER BY k LIMIT 100000")


def test_selection_parity(mixed):
    _assert_parity(
        mixed, "SELECT k, d, v FROM sb WHERE v > 250 LIMIT 50")


def test_double_sum_parity(mixed):
    _assert_parity(
        mixed, "SELECT k, SUM(f), AVG(f) FROM sb "
               "GROUP BY k ORDER BY k LIMIT 1000")


# -- structure guards --------------------------------------------------------

STRUCT_SQL = "SELECT k, SUM(v), COUNT(*) FROM sb GROUP BY k ORDER BY k LIMIT 1000"


def test_single_family_is_one_dispatch(uniform):
    batched = uniform.execute_sql(STRUCT_SQL)
    solo = uniform.execute_sql(NO_BATCH + STRUCT_SQL)
    assert _rows(batched) == _rows(solo)
    # the tentpole: 4 identical segments = 1 family = 1 device dispatch
    assert batched.num_device_dispatches == 1
    assert solo.num_device_dispatches == 4


def test_steady_state_has_zero_compiles(uniform):
    uniform.execute_sql(STRUCT_SQL)  # warm the compile guard
    again = uniform.execute_sql(STRUCT_SQL)
    assert not again.exceptions
    assert again.num_device_dispatches == 1
    assert again.num_compiles == 0


def test_compile_guard_records_one_family_not_s(uniform, monkeypatch):
    guard = executor_mod._CompileCacheGuard()
    monkeypatch.setattr(executor_mod, "_GUARD", guard)
    resp = uniform.execute_sql(STRUCT_SQL)
    assert not resp.exceptions
    # one guard entry for the whole 4-segment query — the batched key, with
    # the batch size as its trailing component — NOT one entry per segment
    assert len(guard._seen) == 1
    (key,) = guard._seen
    assert key[0] == "batch"
    assert key[-1] == 4


def test_mixed_buckets_split_into_two_families(mixed):
    batched = mixed.execute_sql(STRUCT_SQL)
    solo = mixed.execute_sql(NO_BATCH + STRUCT_SQL)
    assert _rows(batched) == _rows(solo)
    # 6000+3000 share the 8192 pad bucket; 9000 pads to 16384: 2 families
    assert batched.num_device_dispatches == 2
    assert solo.num_device_dispatches == 3


def test_explain_implementation_shows_segment_batch(uniform):
    r = uniform.execute_sql("EXPLAIN IMPLEMENTATION FOR " + STRUCT_SQL)
    ops = [row[0] for row in _rows(r)]
    assert any(op == "SEGMENT_BATCH(families:1, segments:4)" for op in ops), ops
    r2 = uniform.execute_sql(
        NO_BATCH + "EXPLAIN IMPLEMENTATION FOR " + STRUCT_SQL)
    ops2 = [row[0] for row in _rows(r2)]
    assert any(op == "SEGMENT_BATCH(disabled)" for op in ops2), ops2


def test_counters_surface_in_json(uniform):
    r = uniform.execute_sql(STRUCT_SQL)
    j = r.to_json()
    assert j["numDeviceDispatches"] == 1
    assert "numCompiles" in j


# -- cache/OOM regression guards ---------------------------------------------


class _FakeSeg:
    num_docs = 100


class _FakeSnap:
    num_docs = 100
    is_mutable = True


def test_stacked_view_survives_budget_pressure():
    # regression: with segment views alone over budget, registering a new
    # stack used to drain _stack_order (the fresh 0-byte stack included)
    # and then KeyError on the return read
    from pinot_tpu.segment.device_cache import DeviceSegmentCache

    cache = DeviceSegmentCache(budget_bytes=16)
    s1, s2 = _FakeSeg(), _FakeSeg()
    v1 = cache.view(s1)
    v1._planes[("c", "ids")] = np.zeros(64, np.int32)  # 256 bytes > budget
    sv = cache.stacked_view([s1, s2])
    # the just-registered stack must survive the same-call eviction pass
    assert cache.stacked_view([s1, s2]) is sv


def test_snapshot_members_skip_stack_cache():
    # stacks are keyed by member id(); realtime snapshot views are fresh
    # objects per query, so caching them would only pin dead HBM bytes
    from pinot_tpu.segment.device_cache import DeviceSegmentCache

    cache = DeviceSegmentCache()
    imm, snap = _FakeSeg(), _FakeSnap()
    sv1 = cache.stacked_view([imm, snap])
    sv2 = cache.stacked_view([imm, snap])
    assert sv1 is not sv2
    assert not cache._stacks and not cache._stack_order


def test_batched_oom_falls_back_to_per_segment(uniform, monkeypatch):
    # a family near HBM capacity can OOM batched (2x footprint) yet fit
    # per-segment — the dispatcher must fall back, not fail the query
    def boom(*a, **k):
        raise MemoryError("fake HBM OOM")

    monkeypatch.setattr(uniform.tpu, "dispatch_plan_batch", boom)
    resp = uniform.execute_sql(STRUCT_SQL)
    assert _rows(resp) == _rows(uniform.execute_sql(NO_BATCH + STRUCT_SQL))
    assert resp.num_device_dispatches == 4  # per-segment path ran


def test_sparse_combine_batched_oom_falls_back(mixed, monkeypatch):
    def boom(*a, **k):
        raise MemoryError("fake HBM OOM")

    monkeypatch.setattr(mixed.tpu, "dispatch_plan_batch_raw", boom)
    _assert_parity(
        mixed, "SET sparseGroupBy = true; "
               "SELECT k, COUNT(*), SUM(v) FROM sb "
               "GROUP BY k ORDER BY k LIMIT 100000")
