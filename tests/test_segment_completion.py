"""Segment completion FSM: committer election, discard/download, crash
re-election.

Reference: SegmentCompletionManager/FSM tests (pinot-controller/src/test/
.../realtime/SegmentCompletionTest.java) — multiple replica consumers reach
end criteria, the controller elects one committer, losers download, and a
committer that dies between build and commit is replaced after its lease
expires.
"""

from __future__ import annotations

import time

import pytest

from pinot_tpu.cluster.store import PropertyStore
from pinot_tpu.realtime.completion import (
    CATCHUP,
    COMMIT,
    COMMIT_SUCCESS,
    COMMITTED,
    CONTINUE,
    DISCARD,
    FAILED,
    HOLD,
    SegmentCompletionManager,
)
from pinot_tpu.realtime.manager import RealtimeTableDataManager
from pinot_tpu.spi.data_types import Schema
from pinot_tpu.spi.stream import InMemoryStreamRegistry, StreamConfig
from pinot_tpu.spi.table_config import (
    IngestionConfig,
    SegmentsValidationConfig,
    TableConfig,
    TableType,
)

SCHEMA = Schema.build(
    "events",
    dimensions=[("user", "STRING"), ("ts", "LONG")],
    metrics=[("n", "INT")])


def table_config(topic, flush_rows=40):
    return TableConfig(
        table_name="events",
        table_type=TableType.REALTIME,
        validation=SegmentsValidationConfig(time_column_name="ts"),
        ingestion=IngestionConfig(stream_configs={
            "streamType": "inmemory",
            "stream.inmemory.topic.name": topic,
            "realtime.segment.flush.threshold.rows": flush_rows,
        }))


def rows(n, start=0):
    return [{"user": f"u{(start + i) % 5}", "ts": 1_600_000_000_000 + i,
             "n": 1} for i in range(n)]


def wait_until(pred, timeout=20.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.02)
    return False


# -- protocol-level FSM tests -------------------------------------------------


def test_fsm_elects_largest_offset_and_catchup():
    store = PropertyStore()
    mgr = SegmentCompletionManager(store, num_replicas=2, commit_lease_s=10)
    # replica B is behind replica A
    r1 = mgr.segment_consumed("t", "seg__0__0", "B", 80)
    assert r1.status == HOLD  # quorum not reached
    r2 = mgr.segment_consumed("t", "seg__0__0", "A", 100)
    assert r2.status == COMMIT and r2.offset == 100  # A has the max → wins
    r3 = mgr.segment_consumed("t", "seg__0__0", "B", 80)
    assert r3.status == CATCHUP and r3.offset == 100
    r4 = mgr.segment_consumed("t", "seg__0__0", "B", 100)
    assert r4.status == HOLD  # caught up, waiting for the committer

    assert mgr.segment_commit_start("t", "seg__0__0", "A", 100).status == CONTINUE
    # wrong instance / wrong offset cannot commit
    assert mgr.segment_commit_end("t", "seg__0__0", "B", 100, "/x").status == FAILED
    assert mgr.segment_commit_end("t", "seg__0__0", "A", 99, "/x").status == FAILED
    end = mgr.segment_commit_end("t", "seg__0__0", "A", 100, "/deep/seg__0__0")
    assert end.status == COMMIT_SUCCESS
    assert mgr.fsm_state("t", "seg__0__0") == COMMITTED
    rec = store.get("/SEGMENTS/t/seg__0__0")
    assert rec["status"] == "DONE" and rec["committer"] == "A"
    assert rec["endOffset"] == "100"
    # late replica is told to discard + download
    r5 = mgr.segment_consumed("t", "seg__0__0", "B", 100)
    assert r5.status == DISCARD and r5.location == "/deep/seg__0__0"


def test_fsm_reelects_after_lease_expiry():
    store = PropertyStore()
    mgr = SegmentCompletionManager(store, num_replicas=2, commit_lease_s=0.2)
    assert mgr.segment_consumed("t", "s", "A", 50).status == HOLD
    # quorum: tie at 50 breaks on report order → A is the committer, B holds
    assert mgr.segment_consumed("t", "s", "B", 50).status == HOLD
    assert mgr.segment_consumed("t", "s", "A", 50).status == COMMIT
    elected, other = "A", "B"
    time.sleep(0.3)  # committer "dies": lease expires
    r = mgr.segment_consumed("t", "s", other, 50)
    assert r.status == COMMIT  # re-elected
    assert mgr.segment_commit_start("t", "s", other, 50).status == CONTINUE
    # the dead committer coming back late cannot steal the commit
    assert mgr.segment_commit_end("t", "s", elected, 50, "/x").status == FAILED
    assert mgr.segment_commit_end("t", "s", other, 50, "/y").status == COMMIT_SUCCESS


def test_single_replica_decides_after_wait():
    store = PropertyStore()
    mgr = SegmentCompletionManager(store, num_replicas=2, commit_lease_s=5,
                                   decision_wait_s=0.1)
    assert mgr.segment_consumed("t", "s", "A", 10).status == HOLD
    time.sleep(0.15)
    assert mgr.segment_consumed("t", "s", "A", 10).status == COMMIT


# -- integration: replica table managers over one stream ----------------------


@pytest.fixture()
def registry(monkeypatch):
    reg = InMemoryStreamRegistry()
    import pinot_tpu.spi.stream as stream_mod

    monkeypatch.setattr(stream_mod, "GLOBAL_STREAM_REGISTRY", reg)
    return reg


def _total_rows(mgr) -> int:
    return sum(s.num_docs for s in mgr.segments)


def test_two_replicas_one_commit(registry, tmp_path):
    registry.create_topic("ev", num_partitions=1)
    store = PropertyStore()
    completion = SegmentCompletionManager(store, num_replicas=2,
                                          commit_lease_s=5, decision_wait_s=3)
    cfg = table_config("ev")
    a = RealtimeTableDataManager(SCHEMA, cfg, tmp_path / "a",
                                 completion=completion, instance_id="A")
    b = RealtimeTableDataManager(SCHEMA, cfg, tmp_path / "b",
                                 completion=completion, instance_id="B")
    a.start()
    b.start()
    try:
        registry.publish("ev", rows(60))
        assert wait_until(lambda: any(
            n.startswith("events__0__0") for n in a._segment_names)
            and any(n.startswith("events__0__0") for n in b._segment_names)), \
            (a._segment_names, b._segment_names)
        name_a, name_b = a._segment_names[0], b._segment_names[0]
        assert name_a == name_b  # identical LLC segment both sides
        rec = store.get(f"/SEGMENTS/events/{name_a}")
        assert rec is not None and rec["status"] == "DONE"
        assert rec["committer"] in ("A", "B")
        # both replicas serve the same committed rows (40 = flush threshold)
        assert wait_until(lambda: _total_rows(a) == 60 and _total_rows(b) == 60)
        committed_a = a._committed[0]
        committed_b = b._committed[0]
        assert committed_a.num_docs == committed_b.num_docs
        assert list(committed_a.get_values("user")) == \
            list(committed_b.get_values("user"))
        # loser downloaded into its OWN data dir
        assert (tmp_path / "a" / name_a).exists()
        assert (tmp_path / "b" / name_a).exists()
    finally:
        a.stop()
        b.stop()


def test_committer_crash_reelection_end_to_end(registry, tmp_path):
    registry.create_topic("ev2", num_partitions=1)
    store = PropertyStore()
    completion = SegmentCompletionManager(store, num_replicas=2,
                                          commit_lease_s=1.5,
                                          decision_wait_s=3)
    cfg = table_config("ev2")
    killed = {"done": False}

    def die_once(mgr):
        # the FIRST elected committer (seq 0) dies between build and commit
        if mgr.seq == 0 and not killed["done"]:
            killed["done"] = True
            return True
        return False

    hooks = {"die_before_commit_end": die_once}
    a = RealtimeTableDataManager(SCHEMA, cfg, tmp_path / "a",
                                 completion=completion, instance_id="A",
                                 test_hooks=hooks)
    b = RealtimeTableDataManager(SCHEMA, cfg, tmp_path / "b",
                                 completion=completion, instance_id="B",
                                 test_hooks=hooks)
    a.start()
    b.start()
    try:
        registry.publish("ev2", rows(50))
        # exactly one replica's consumer died; the OTHER must be re-elected
        # after the lease expires and commit the segment
        assert wait_until(lambda: store.children("/SEGMENTS/events"),
                          timeout=25)
        seg_name = store.children("/SEGMENTS/events")[0]
        rec = store.get(f"/SEGMENTS/events/{seg_name}")
        assert rec["status"] == "DONE"
        assert killed["done"]
        survivor = rec["committer"]
        surv_mgr = a if survivor == "A" else b
        assert wait_until(lambda: _total_rows(surv_mgr) >= 40)
        # the DONE store record lands before the committer's local
        # _committed list update (separate thread) — wait, don't race it
        assert wait_until(lambda: surv_mgr._committed)
        committed = surv_mgr._committed[0]
        # all 50 published rows: end criteria is checked after the batch
        assert committed.num_docs == 50
    finally:
        a.stop()
        b.stop()


def test_chaos_replica_killed_mid_ingestion_recovers(registry, tmp_path):
    """ChaosMonkey analogue: one replica dies mid-consumption, ingestion
    continues on the survivor; the dead replica restarts from its checkpoint
    and converges (downloading segments committed while it was down)."""
    registry.create_topic("ev3", num_partitions=1)
    store = PropertyStore()
    completion = SegmentCompletionManager(store, num_replicas=2,
                                          commit_lease_s=1.5,
                                          decision_wait_s=0.5)
    cfg = table_config("ev3", flush_rows=20)
    a = RealtimeTableDataManager(SCHEMA, cfg, tmp_path / "a",
                                 completion=completion, instance_id="A")
    b = RealtimeTableDataManager(SCHEMA, cfg, tmp_path / "b",
                                 completion=completion, instance_id="B")
    a.start()
    b.start()
    registry.publish("ev3", rows(20))
    assert wait_until(lambda: _total_rows(a) == 20 and _total_rows(b) == 20,
                      timeout=60)

    # chaos: replica A dies mid-stream
    a.stop()
    registry.publish("ev3", rows(40, start=20))
    # B alone keeps committing (decision_wait elapses with a single voter)
    assert wait_until(lambda: _total_rows(b) == 60
                      and len(b._segment_names) >= 2, timeout=60), \
        (_total_rows(b), b._segment_names)

    # A restarts from its checkpoint and converges to the same row count,
    # downloading the segments B committed while A was down
    a2 = RealtimeTableDataManager(SCHEMA, cfg, tmp_path / "a",
                                  completion=completion, instance_id="A")
    a2.start()
    try:
        assert wait_until(lambda: _total_rows(a2) == 60
                          and a2._segment_names == b._segment_names,
                          timeout=60), \
            (_total_rows(a2), a2._segment_names, b._segment_names)
        # every committed segment now exists in BOTH data dirs
        for name in b._segment_names:
            assert (tmp_path / "a" / name).exists()
            assert (tmp_path / "b" / name).exists()
    finally:
        a2.stop()
        b.stop()


def test_committed_record_carries_partition_stamps(registry, tmp_path):
    """A partitioned realtime table's DONE record includes the builder's
    partition stamps, so the MSE dispatcher can place colocated workers
    next to realtime segments too."""
    from pinot_tpu.spi.table_config import IndexingConfig

    registry.create_topic("evp", num_partitions=1)
    store = PropertyStore()
    completion = SegmentCompletionManager(store, num_replicas=1,
                                          commit_lease_s=5, decision_wait_s=0.1)
    cfg = table_config("evp", flush_rows=20)
    cfg.indexing = IndexingConfig(segment_partition_config={
        "n": {"functionName": "modulo", "numPartitions": 4}})
    a = RealtimeTableDataManager(SCHEMA, cfg, tmp_path / "a",
                                 completion=completion, instance_id="A")
    a.start()
    try:
        registry.publish("evp", rows(20))
        assert wait_until(lambda: store.children("/SEGMENTS/events"))
        name = store.children("/SEGMENTS/events")[0]
        rec = store.get(f"/SEGMENTS/events/{name}")
        assert rec["status"] == "DONE"
        p = rec["partitions"]["n"]
        assert p["functionName"] == "modulo" and p["numPartitions"] == 4
        assert p["partitions"] == [1]  # every row has n = 1
    finally:
        a.stop()
