"""Self-healing scatter/gather: replica retries with backoff, hedged
requests, per-server circuit breakers, and broker admission control.

Every cluster-level scenario is driven by the deterministic fault
registry (spi/faults.py) — explicit times=N / call-index schedules, no
sleep-and-hope — and asserts the PR invariant ladder:

    retry → hedge → breaker → partial → reject

A healable fault must heal to the bit-identical full answer
(partialResult=false); only replica exhaustion degrades exactly like the
graceful-degradation layer; overload sheds with a well-formed 429-style
rejection, never a pile-up.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from pinot_tpu.cluster import (Broker, ClusterController, PropertyStore,
                               ServerInstance)
from pinot_tpu.cluster.breaker import (CLOSED, HALF_OPEN, OPEN,
                                       CircuitBreakerTable)
from pinot_tpu.cluster.quota import (AdmissionController,
                                     AdmissionRejectedError)
from pinot_tpu.engine.scheduler import (QueryKilledError, ResourceAccountant)
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.spi import faults
from pinot_tpu.spi.data_types import Schema
from pinot_tpu.spi.metrics import BROKER_METRICS, SERVER_METRICS, \
    BrokerMeter, ServerMeter

SCHEMA = Schema.build(
    "shstats",
    dimensions=[("team", "STRING")],
    metrics=[("runs", "INT")])
TEAMS = ["BOS", "NYA", "SFN", "LAN"]
N_SEGMENTS = 4
ROWS = 80

# faults must reach transport/server on every run — no cache shortcuts
NOCACHE = "SET resultCache = false; SET segmentCache = false; "
SQL = NOCACHE + "SELECT team, SUM(runs) FROM shstats GROUP BY team LIMIT 20"


@pytest.fixture(autouse=True)
def _reset_faults():
    yield
    faults.FAULTS.reset()


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    d = tmp_path_factory.mktemp("self_healing")
    store = PropertyStore()
    controller = ClusterController(store)
    servers = [ServerInstance(store, f"Server_{i}", backend="host")
               for i in range(2)]
    for s in servers:
        s.start()
    controller.add_schema(SCHEMA.to_json())
    table = controller.create_table({"tableName": "shstats",
                                     "replication": 2})
    rng = np.random.default_rng(20260805)
    expected: dict[str, int] = {}
    for i in range(N_SEGMENTS):
        cols = {
            "team": np.asarray(TEAMS, dtype=object)[
                rng.integers(0, len(TEAMS), ROWS)],
            "runs": rng.integers(0, 100, ROWS).astype(np.int32),
        }
        name = f"shstats_{i}"
        SegmentBuilder(SCHEMA, segment_name=name).build(cols, d / name)
        controller.add_segment(table, name,
                               {"location": str(d / name), "numDocs": ROWS})
        for t, r in zip(cols["team"], cols["runs"]):
            expected[t] = expected.get(t, 0) + int(r)
    yield store, servers, expected
    for s in servers:
        try:
            s.stop()
        except Exception:
            pass


def _fresh_broker(store, **kw) -> Broker:
    """Each test that arms faults or trips breakers gets its own broker:
    breaker state is per-broker and must not leak across tests."""
    b = Broker(store, **kw)
    b.backoff_base_s = 0.001  # keep retry tests fast; bound tests override
    return b


def _exact(resp, expected):
    assert resp.result_table is not None, resp.exceptions
    assert {r[0]: r[1] for r in resp.result_table.rows} == expected


# ════════════════════════════════════════════════════════════════════════════
# circuit breaker unit lifecycle
# ════════════════════════════════════════════════════════════════════════════


def test_breaker_opens_after_consecutive_failures():
    t = CircuitBreakerTable(failure_threshold=3, cooldown_s=60.0,
                            metrics=None)
    for _ in range(2):
        t.record_failure("s1")
    assert t.state("s1") == CLOSED and t.allow("s1")
    t.record_failure("s1")
    assert t.state("s1") == OPEN
    assert not t.allow("s1")
    assert t.down_count() == 1


def test_breaker_half_open_admits_single_probe_then_closes():
    t = CircuitBreakerTable(failure_threshold=1, cooldown_s=0.05,
                            metrics=None)
    t.record_failure("s1")
    assert not t.allow("s1")
    time.sleep(0.06)
    assert t.state("s1") == HALF_OPEN
    assert t.allow("s1")          # this caller carries the probe
    assert not t.allow("s1")      # one probe at a time
    t.record_success("s1")
    assert t.state("s1") == CLOSED
    assert t.allow("s1") and t.down_count() == 0


def test_breaker_failed_probe_reopens_with_doubled_cooldown():
    t = CircuitBreakerTable(failure_threshold=1, cooldown_s=0.05,
                            metrics=None)
    t.record_failure("s1")
    time.sleep(0.06)
    assert t.allow("s1")  # probe
    t.record_failure("s1")  # probe failed
    assert t.state("s1") == OPEN
    snap = t.snapshot()["s1"]
    assert snap["cooldownS"] == pytest.approx(0.1, rel=0.01)
    assert snap["timesOpened"] == 2
    # a later success closes AND resets the cooldown to base
    time.sleep(0.11)
    assert t.allow("s1")
    t.record_success("s1")
    assert t.snapshot()["s1"]["cooldownS"] == pytest.approx(0.05, rel=0.01)


def test_breaker_success_resets_consecutive_count():
    t = CircuitBreakerTable(failure_threshold=3, cooldown_s=60.0,
                            metrics=None)
    t.record_failure("s1")
    t.record_failure("s1")
    t.record_success("s1")
    t.record_failure("s1")
    t.record_failure("s1")
    assert t.state("s1") == CLOSED  # never 3 consecutive


def test_breaker_error_rate_trip():
    t = CircuitBreakerTable(failure_threshold=100, cooldown_s=60.0,
                            error_rate_threshold=0.5,
                            error_rate_min_volume=8, metrics=None)
    # interleave so consecutive-failure never trips: 4 ok, then 4 fail
    for _ in range(4):
        t.record_success("s1")
    for _ in range(3):
        t.record_failure("s1")
    assert t.state("s1") == CLOSED  # 3/7 < 0.5 (and below min volume)
    t.record_failure("s1")
    assert t.state("s1") == OPEN  # 4/8 >= 0.5 at min volume


# ════════════════════════════════════════════════════════════════════════════
# admission control + tombstones (unit)
# ════════════════════════════════════════════════════════════════════════════


def test_admission_queue_full_rejects_immediately():
    a = AdmissionController(max_inflight=1, max_queued=0)
    ctx = a.admit(timeout_s=5.0)
    ctx.__enter__()
    try:
        t0 = time.perf_counter()
        with pytest.raises(AdmissionRejectedError, match="queue full"):
            with a.admit(timeout_s=5.0):
                pass
        assert time.perf_counter() - t0 < 1.0  # no deadline-long wait
    finally:
        ctx.__exit__(None, None, None)


def test_admission_queue_wait_bounded_by_deadline():
    a = AdmissionController(max_inflight=1, max_queued=4)
    ctx = a.admit(timeout_s=5.0)
    ctx.__enter__()
    try:
        t0 = time.perf_counter()
        with pytest.raises(AdmissionRejectedError, match="deadline"):
            with a.admit(timeout_s=0.1):
                pass
        elapsed = time.perf_counter() - t0
        assert 0.08 <= elapsed < 1.0
    finally:
        ctx.__exit__(None, None, None)
    # slot free again: admission proceeds
    with a.admit(timeout_s=0.1):
        assert a.inflight() == 1
    assert a.inflight() == 0


def test_admission_disabled_is_a_noop():
    a = AdmissionController(max_inflight=None)
    with a.admit(timeout_s=0.0):
        pass  # never rejects


def test_tombstone_cancel_before_register():
    acc = ResourceAccountant()
    # the cancel arrives FIRST (lost race): unknown id → False, but
    # tombstoned
    assert acc.kill_query("late_q", reason="deadline") is False
    t = acc.start_query("late_q")
    with pytest.raises(QueryKilledError, match="deadline"):
        t.check_cancel()
    acc.end_query(t)


def test_tombstone_expires():
    acc = ResourceAccountant(tombstone_ttl_s=0.05)
    acc.kill_query("q_exp")
    time.sleep(0.08)
    t = acc.start_query("q_exp")
    t.check_cancel()  # no raise: tombstone expired
    acc.end_query(t)


def test_kill_prefix_kills_live_shards_and_late_arrivals():
    acc = ResourceAccountant()
    t0 = acc.start_query("abc:0")
    t1 = acc.start_query("abc:1")
    other = acc.start_query("abcd:0")  # NOT a shard of "abc"
    assert acc.kill_prefix("abc", reason="broker gave up") == 2
    for t in (t0, t1):
        with pytest.raises(QueryKilledError):
            t.check_cancel()
    other.check_cancel()  # unaffected
    # a shard that registers after the prefix cancel dies on arrival
    late = acc.start_query("abc:7")
    with pytest.raises(QueryKilledError):
        late.check_cancel()
    for t in (t0, t1, other, late):
        acc.end_query(t)


# ════════════════════════════════════════════════════════════════════════════
# replica retry with backoff (cluster)
# ════════════════════════════════════════════════════════════════════════════


def test_retry_heals_transport_error_full_result(cluster):
    store, _servers, expected = cluster
    broker = _fresh_broker(store)
    resp = broker.execute_sql(SQL)
    assert not resp.exceptions
    m0 = BROKER_METRICS.meter_count(BrokerMeter.SCATTER_RETRIES)
    faults.FAULTS.arm("transport.call", faults.FaultSpec(kind="error",
                                                         times=1))
    resp = broker.execute_sql(SQL)
    assert not resp.exceptions
    assert resp.partial_result is False  # healed, NOT degraded
    assert resp.num_scatter_retries >= 1
    assert resp.to_json()["numScatterRetries"] == resp.num_scatter_retries
    assert BROKER_METRICS.meter_count(BrokerMeter.SCATTER_RETRIES) > m0
    _exact(resp, expected)


def test_retry_heals_dropped_connection(cluster):
    store, _servers, expected = cluster
    broker = _fresh_broker(store)
    faults.FAULTS.arm("transport.call", faults.FaultSpec(kind="drop",
                                                         times=1))
    resp = broker.execute_sql(SQL)
    assert not resp.exceptions and resp.partial_result is False
    assert resp.num_scatter_retries >= 1
    _exact(resp, expected)


def test_all_replicas_exhausted_fails_loudly_without_partial(cluster):
    store, _servers, _expected = cluster
    broker = _fresh_broker(store)
    faults.FAULTS.arm("transport.call", faults.FaultSpec(kind="error",
                                                         times=20))
    resp = broker.execute_sql(SQL)
    assert resp.exceptions
    assert "unreachable on all replicas" in resp.exceptions[0]
    assert resp.result_table is None and not resp.partial_result


def test_all_replicas_exhausted_degrades_like_pr6_partial(cluster):
    store, _servers, _expected = cluster
    broker = _fresh_broker(store)
    faults.FAULTS.arm("transport.call", faults.FaultSpec(kind="error",
                                                         times=20))
    resp = broker.execute_sql("SET allowPartialResults=true; " + SQL)
    # the PR 6 contract, unchanged: well-formed partial with per-server
    # exceptions, never a silent wrong answer
    assert resp.partial_result is True
    assert resp.exceptions and resp.result_table is not None
    assert resp.to_json()["partialResult"] is True


def test_backoff_is_bounded_by_deadline(cluster):
    store, _servers, _expected = cluster
    broker = _fresh_broker(store)
    broker.backoff_base_s = 30.0  # pathological backoff…
    broker.backoff_cap_s = 30.0
    faults.FAULTS.arm("transport.call", faults.FaultSpec(kind="error",
                                                         times=20))
    t0 = time.perf_counter()
    resp = broker.execute_sql("SET timeoutMs=400; " + SQL)
    elapsed = time.perf_counter() - t0
    assert resp.exceptions  # …but the query still fails within its budget
    assert elapsed < 5.0, f"backoff ignored the deadline: {elapsed:.1f}s"


def test_healthy_path_bit_identical_with_zero_healing_counters(cluster):
    store, _servers, expected = cluster
    broker = _fresh_broker(store)
    a = broker.execute_sql(SQL)
    b = broker.execute_sql(SQL)
    assert not a.exceptions and not b.exceptions
    assert [list(r) for r in a.result_table.rows] == \
        [list(r) for r in b.result_table.rows]
    for resp in (a, b):
        assert resp.num_scatter_retries == 0
        assert resp.num_hedged_requests == 0
        assert resp.num_hedge_wins == 0
        j = resp.to_json()
        for k in ("numScatterRetries", "numHedgedRequests", "queryRejected"):
            assert k not in j
        _exact(resp, expected)


# ════════════════════════════════════════════════════════════════════════════
# hedged requests
# ════════════════════════════════════════════════════════════════════════════


def test_hedge_beats_straggler(cluster):
    store, _servers, expected = cluster
    broker = _fresh_broker(store, hedge_ms=40.0)
    resp = broker.execute_sql(SQL)  # warm (compile) before timing
    assert not resp.exceptions
    m0 = BROKER_METRICS.meter_count(BrokerMeter.HEDGE_WINS)
    # first server.query of the next query stalls 1.5s — the hedge fires
    # at 40ms on the other replica and wins
    faults.FAULTS.arm("server.query", faults.FaultSpec(
        kind="delay", delay_s=1.5, schedule=frozenset({0})))
    t0 = time.perf_counter()
    resp = broker.execute_sql(SQL)
    elapsed = time.perf_counter() - t0
    assert not resp.exceptions and resp.partial_result is False
    assert resp.num_hedged_requests >= 1
    assert resp.num_hedge_wins >= 1
    assert BROKER_METRICS.meter_count(BrokerMeter.HEDGE_WINS) > m0
    assert elapsed < 1.2, f"hedge did not rescue the straggler: {elapsed:.2f}s"
    _exact(resp, expected)
    j = resp.to_json()
    assert j["numHedgedRequests"] == resp.num_hedged_requests
    assert j["numHedgeWins"] == resp.num_hedge_wins


def test_hedge_dedupe_is_bit_identical_to_unhedged(cluster):
    store, _servers, expected = cluster
    plain = _fresh_broker(store)
    oracle = plain.execute_sql(SQL)
    assert not oracle.exceptions
    # hedge virtually every shard (1µs delay): duplicates race the
    # primaries, first-complete-wins must still merge exactly one response
    # per shard, in shard order
    hedgy = _fresh_broker(store, hedge_ms=0.001)
    for _ in range(3):
        resp = hedgy.execute_sql(SQL)
        assert not resp.exceptions and resp.partial_result is False
        assert [list(r) for r in resp.result_table.rows] == \
            [list(r) for r in oracle.result_table.rows]
        _exact(resp, expected)
    assert resp.num_hedged_requests >= 1


def test_hedge_disabled_by_default(cluster):
    store, _servers, _expected = cluster
    broker = _fresh_broker(store)
    assert broker._hedge_delay_s() is None
    # quantile mode stays off until the histogram has enough samples
    broker.hedge_quantile = 0.95
    assert broker.hedge_fixed_ms is None
    # (may or may not be None here depending on global histogram volume —
    # just must not crash); fixed "0" always disables
    broker.hedge_fixed_ms = 0.0
    assert broker._hedge_delay_s() is None


# ════════════════════════════════════════════════════════════════════════════
# circuit breaker integration
# ════════════════════════════════════════════════════════════════════════════


def test_tripped_breaker_reroutes_all_traffic(cluster):
    store, _servers, expected = cluster
    broker = _fresh_broker(store)
    m0 = BROKER_METRICS.meter_count(BrokerMeter.CIRCUIT_OPEN)
    for _ in range(3):  # default threshold
        broker.breakers.record_failure("Server_0")
    assert broker.breakers.state("Server_0") == OPEN
    assert BROKER_METRICS.meter_count(BrokerMeter.CIRCUIT_OPEN) == m0 + 1
    assert broker.breakers.down_count() == 1
    assert BROKER_METRICS.gauge_value("serversUnhealthy") == 1
    assert BROKER_METRICS.gauge_value("circuitBreakerState.Server_0") == 2
    resp = broker.execute_sql(SQL)
    assert not resp.exceptions
    assert resp.num_servers_queried == 1  # everything routed to Server_1
    _exact(resp, expected)


def test_breaker_closes_after_successful_probe_traffic(cluster):
    store, _servers, expected = cluster
    broker = _fresh_broker(store)
    broker.breakers.base_cooldown_s = 0.05
    b = broker.breakers._breaker_locked("Server_0")
    b.cooldown_s = 0.05
    for _ in range(3):
        broker.breakers.record_failure("Server_0")
    time.sleep(0.06)
    assert broker.breakers.state("Server_0") == HALF_OPEN
    # the server is actually fine: the next scatter probes it and the
    # success closes the breaker
    resp = broker.execute_sql(SQL)
    assert not resp.exceptions
    _exact(resp, expected)
    deadline = time.monotonic() + 2.0
    while broker.breakers.state("Server_0") != CLOSED \
            and time.monotonic() < deadline:
        broker.execute_sql(SQL)
    assert broker.breakers.state("Server_0") == CLOSED


# ════════════════════════════════════════════════════════════════════════════
# admission control (broker + REST)
# ════════════════════════════════════════════════════════════════════════════


def test_admission_rejection_under_synthetic_overload(cluster):
    store, _servers, expected = cluster
    broker = _fresh_broker(store)
    broker.admission = AdmissionController(max_inflight=1, max_queued=0)
    m0 = BROKER_METRICS.meter_count(BrokerMeter.QUERIES_REJECTED)
    # stall the first query inside the cluster for 0.6s so it holds the
    # only admission slot
    faults.FAULTS.arm("server.query", faults.FaultSpec(
        kind="delay", delay_s=0.6, times=1))
    results = {}

    def slow_query():
        results["slow"] = broker.execute_sql(SQL)

    t = threading.Thread(target=slow_query)
    t.start()
    time.sleep(0.2)  # let the slow query take the slot
    rejected = broker.execute_sql(SQL)
    t.join()
    assert rejected.query_rejected is True
    assert rejected.exceptions
    assert rejected.exceptions[0].startswith("QueryRejectedError")
    assert rejected.to_json()["queryRejected"] is True
    assert BROKER_METRICS.meter_count(BrokerMeter.QUERIES_REJECTED) == m0 + 1
    # the admitted query still completed exactly
    assert not results["slow"].exceptions
    _exact(results["slow"], expected)


def test_rest_returns_429_and_debug_servers(cluster):
    from pinot_tpu.cluster.rest import BrokerRestServer

    store, _servers, expected = cluster
    broker = _fresh_broker(store)
    broker.admission = AdmissionController(max_inflight=1, max_queued=0)
    broker.breakers.record_failure("Server_0")  # visible in /debug/servers
    rest = BrokerRestServer(broker)
    try:
        held = broker.admission.admit(timeout_s=5.0)
        held.__enter__()
        try:
            req = urllib.request.Request(
                rest.url + "/query/sql",
                data=json.dumps({"sql": SQL}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 429
            body = json.loads(ei.value.read())
            assert body["queryRejected"] is True
            # breaker table visible while nothing has healed it yet
            with urllib.request.urlopen(rest.url + "/debug/servers") as r:
                dbg = json.loads(r.read())
            assert dbg["servers"]["Server_0"]["consecutiveFailures"] == 1
            assert dbg["servers"]["Server_0"]["state"] == "closed"
        finally:
            held.__exit__(None, None, None)
        # freed: same query now succeeds over REST
        with urllib.request.urlopen(urllib.request.Request(
                rest.url + "/query/sql",
                data=json.dumps({"sql": SQL}).encode(),
                headers={"Content-Type": "application/json"})) as r:
            body = json.loads(r.read())
        assert {x[0]: x[1] for x in body["resultTable"]["rows"]} == expected
        with urllib.request.urlopen(rest.url + "/metrics") as r:
            text = r.read().decode()
        assert "circuitBreakerState_Server_0" in text
    finally:
        rest.close()


# ════════════════════════════════════════════════════════════════════════════
# cancel-before-register (cluster) + broker.route + querylog
# ════════════════════════════════════════════════════════════════════════════


def test_prefix_cancel_rpc_kills_shards(cluster):
    store, servers, _expected = cluster
    broker = _fresh_broker(store)
    acc = servers[0].scheduler.accountant
    t0 = acc.start_query("pfx:0")
    t1 = acc.start_query("pfx:1")
    out = broker._client("Server_0").call(
        {"type": "cancel", "queryId": "pfx", "prefix": True,
         "reason": "test cancel"})
    assert out == {"cancelled": True}
    for t in (t0, t1):
        with pytest.raises(QueryKilledError):
            t.check_cancel()
        acc.end_query(t)
    # exact-id cancel of an unknown query still reports False (and
    # tombstones it server-side)
    out = broker._client("Server_0").call(
        {"type": "cancel", "queryId": "nosuch"})
    assert out == {"cancelled": False}


def test_deadline_cancel_lands_before_shard_registers(cluster):
    """The cancel-before-register race, end to end: both shard handlers
    stall (explicit call-index fault schedule) past the broker deadline,
    the broker's prefix cancel arrives while NOTHING is registered yet,
    and the tombstone still kills the shards when they finally register."""
    store, _servers, _expected = cluster
    broker = _fresh_broker(store)
    killed0 = SERVER_METRICS.meter_count(ServerMeter.QUERIES_KILLED)
    # the server.query fault fires BEFORE scheduler.submit registers the
    # tracker, so the delay opens the race window deterministically; it
    # must outlast the broker's socket timeout (remaining + 2s slack) so
    # the broker abandons the query and fires the prefix cancel while the
    # handlers are still asleep — i.e. before anything registered
    faults.FAULTS.arm("server.query", faults.FaultSpec(
        kind="delay", delay_s=3.0, times=None, schedule=frozenset({0, 1})))
    t0 = time.perf_counter()
    resp = broker.execute_sql("SET timeoutMs=250; " + SQL)
    assert resp.exceptions  # deadline exceeded
    assert any("TimeoutError" in x or "deadline" in x
               for x in resp.exceptions), resp.exceptions
    assert time.perf_counter() - t0 < 10.0
    # handlers wake AFTER the cancel: the tombstone must kill them at
    # their first segment boundary
    deadline = time.monotonic() + 4.0
    while SERVER_METRICS.meter_count(ServerMeter.QUERIES_KILLED) <= killed0 \
            and time.monotonic() < deadline:
        time.sleep(0.05)
    assert SERVER_METRICS.meter_count(ServerMeter.QUERIES_KILLED) > killed0


def test_broker_route_fault_point(cluster):
    store, _servers, expected = cluster
    assert "broker.route" in faults.POINTS
    broker = _fresh_broker(store)
    faults.FAULTS.arm("broker.route", faults.FaultSpec(kind="error",
                                                       times=1))
    resp = broker.execute_sql(SQL)
    assert resp.exceptions
    assert "injected fault at broker.route" in resp.exceptions[0]
    assert faults.FAULTS.fired("broker.route") == 1
    # next routing read is clean
    resp = broker.execute_sql(SQL)
    assert not resp.exceptions
    _exact(resp, expected)


def test_querylog_records_healing_fields(cluster):
    store, _servers, _expected = cluster
    broker = _fresh_broker(store)
    broker.query_logger.slow_threshold_ms = 0.0  # capture everything
    faults.FAULTS.arm("transport.call", faults.FaultSpec(kind="error",
                                                         times=1))
    resp = broker.execute_sql(SQL)
    assert not resp.exceptions and resp.num_scatter_retries >= 1
    entries = broker.query_logger.slow_queries()
    assert entries
    assert entries[-1]["scatterRetries"] == resp.num_scatter_retries
    assert "hedgedRequests" not in entries[-1]


# ════════════════════════════════════════════════════════════════════════════
# soak --qps smoke
# ════════════════════════════════════════════════════════════════════════════


def test_soak_qps_smoke():
    from pinot_tpu.tools.soak import soak_qps

    out = soak_qps(seconds=3.0, seed=7, qps=20.0, concurrency=3,
                   n_servers=2, n_segments=3, rows_per_segment=60,
                   fault_rate=0.02)
    assert out["suite"] == "qps"
    assert out["queries_ok"] > 0
    assert out["p50_ms"] is not None and out["p99_ms"] >= out["p50_ms"]
    assert out["achieved_qps"] > 0
    # the armed schedule produced work for the healing layer (retries) —
    # and every full answer was exact (soak_qps raises otherwise)
    assert out["scatter_retries"] + out["queries_degraded"] >= 0


def test_soak_qps_family_rotation_exact():
    """``--families`` traffic-shift mode: the run rotates through
    distinct query families and verifies EVERY family's full responses
    against precomputed aggregates (soak_qps raises on any mismatch).
    Host backend keeps this compile-free and fast; the tpu-backend
    AOT-on/off comparison is the slow CLI form of the same run."""
    from pinot_tpu.tools.soak import soak_qps

    out = soak_qps(seconds=4.0, seed=11, qps=25.0, concurrency=4,
                   n_servers=2, n_segments=3, rows_per_segment=80,
                   families=5)
    assert out["families"] == 5
    assert out["backend"] == "host"
    assert out["num_compiles"] == 0  # host engine never compiles
    # enough queries ran that every family's window saw traffic
    assert out["queries_ok"] >= 5
