"""End-to-end regression sentinel over a live broker REST surface.

The acceptance path for the continuous regression sentinel: a seeded
``device.dispatch`` delay fault slows a live cluster's dispatches; the
sentinel classifies the shift as ``latency-drift`` within its hysteresis
budget; the alert shows at GET /debug/alerts with at least one pinned
exemplar trace retrievable (chrome format included) by alert id; the
alert auto-clears once clean windows accumulate; and the persisted
ledger survives a WAL-store restart.

Companions: test_perf_ledger.py (unit), test_tracing_perf_guard.py
(warm-path zero-cost), soak.py --suite sentinel (the same loop
time-boxed for long runs).
"""

from __future__ import annotations

import json
import tempfile
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from pinot_tpu.cluster import (Broker, ClusterController, PropertyStore,
                               ServerInstance)
from pinot_tpu.cluster.sentinel import (SENTINEL_REPORT_PATH,
                                        PerfRegressionSentinel)
from pinot_tpu.engine.perf_ledger import (ALERTS, LEDGER_PATH, PERF_LEDGER,
                                          PerfLedger)
from pinot_tpu.spi import faults
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.spi.data_types import Schema

SCHEMA = Schema.build("sentab", dimensions=[("sk", "STRING")],
                      metrics=[("sv", "INT")])
# both caches off: a cached repeat performs zero device dispatches, so
# neither the delay fault nor the drift it should cause would exist
SQL = ("SET resultCache = false; SET segmentCache = false; "
       "SELECT sk, SUM(sv) FROM sentab GROUP BY sk")


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    d = tmp_path_factory.mktemp("sentinel_rest")
    PERF_LEDGER.clear()
    ALERTS.clear()
    store = PropertyStore(data_dir=str(d / "store"), fsync="off")
    controller = ClusterController(store)
    # backend="auto": the fault point sits on the device dispatch path
    server = ServerInstance(store, "Server_0", backend="auto")
    server.start()
    controller.add_schema(SCHEMA.to_json())
    controller.create_table({"tableName": "sentab", "replication": 1})
    rng = np.random.default_rng(19)
    for i in range(2):
        n = 200
        cols = {"sk": np.asarray(["a", "b", "c", "d"], dtype=object)[
                    rng.integers(0, 4, n)],
                "sv": rng.integers(0, 100, n).astype(np.int32)}
        name = f"sentab_{i}"
        SegmentBuilder(SCHEMA, segment_name=name).build(cols, d / name)
        controller.add_segment("sentab_OFFLINE", name,
                               {"location": str(d / name), "numDocs": n})
    broker = Broker(store)
    yield store, controller, server, broker, d
    faults.FAULTS.reset()
    PERF_LEDGER.clear()
    ALERTS.clear()
    server.stop()
    store.close()


def _burst(broker, n):
    for _ in range(n):
        resp = broker.execute_sql(SQL)
        assert not resp.exceptions, resp.exceptions


def _get(rs, path):
    with urllib.request.urlopen(rs.url + path) as r:
        return r.status, json.loads(r.read())


def test_sentinel_detects_pins_and_clears_over_rest(cluster):
    from pinot_tpu.cluster.rest import BrokerRestServer

    store, controller, _server, broker, _d = cluster
    _burst(broker, 8)
    PERF_LEDGER.rotate_now()
    sentinel = PerfRegressionSentinel(store, controller, min_queries=3,
                                      breaches=2, clears=2)
    report = sentinel.evaluate()
    assert report["anomalies"] == [], report["anomalies"]

    rs = BrokerRestServer(broker)
    try:
        # ledger endpoint serves the baseline plan
        code, ledger = _get(rs, "/debug/ledger")
        assert code == 200 and ledger["numPlans"] >= 1
        assert ledger["plans"][0]["totals"]["queries"] >= 8

        # -- inject: every dispatch +50ms -------------------------------
        alert = None
        with faults.injected("device.dispatch", kind="delay",
                             delay_s=0.05, times=None):
            for _ in range(12):
                _burst(broker, 6)
                sentinel.evaluate()
                if ALERTS.active_count:
                    alert = ALERTS.active()[0]
                    break
            assert alert is not None, \
                "injected dispatch delay never raised an alert"
            assert alert["type"] == "latency-drift"
            # exemplar arming: next matching queries are force-traced
            _burst(broker, 4)

        code, alerts = _get(rs, "/debug/alerts")
        assert code == 200 and alerts["active"] >= 1
        assert any(a["id"] == alert["id"] for a in alerts["alerts"])

        code, rec = _get(rs, f"/debug/alerts/{alert['id']}")
        assert code == 200 and rec["type"] == "latency-drift"
        exemplars = rec.get("exemplarTraceIds") or []
        assert exemplars, "alert fired but pinned no exemplar traces"

        # the pinned exemplar is a real retained trace, chrome-exportable,
        # cross-linked back to its alert
        tid = exemplars[0]
        code, trace = _get(rs, f"/debug/traces/{tid}")
        assert code == 200 and alert["id"] in trace.get("alertIds", [])
        code, chrome = _get(rs, f"/debug/traces/{tid}?format=chrome")
        assert code == 200 and chrome["traceEvents"], \
            "exemplar must export as a chrome trace"

        # slow-log cross-link: entries during the incident name the alert
        slow = broker.query_logger.slow_queries()
        linked = [e for e in slow if alert["id"] in e.get("alertIds", [])]
        # (only present if any query crossed the slow threshold — the
        # 50ms delay is under the 500ms default, so don't require it;
        # active_ids_for is covered by unit tests)
        for e in linked:
            assert e["table"] == "sentab"

        try:
            _get(rs, "/debug/alerts/no-such-alert")
            assert False, "404 expected"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        faults.FAULTS.reset()
        rs.close()

    # -- recovery: clean rounds resolve the alert -----------------------
    for _ in range(12):
        _burst(broker, 6)
        sentinel.evaluate()
        if not ALERTS.active_count:
            break
    assert ALERTS.active_count == 0, "alert never cleared after recovery"
    rec = ALERTS.get(alert["id"])
    assert rec["state"] == "cleared" and rec["clearReason"] == "recovered"

    # a full scrape pass lands the ledger and report in the store
    sentinel()
    assert store.get(LEDGER_PATH) is not None
    assert store.get(SENTINEL_REPORT_PATH) is not None


def test_ledger_survives_store_restart(cluster, tmp_path):
    """Persist into a durable WAL store, close it, reopen from disk: the
    reference windows come back."""
    assert len(PERF_LEDGER) >= 1, "e2e test must have populated the ledger"
    wal = PropertyStore(data_dir=str(tmp_path / "wal"), fsync="off")
    PERF_LEDGER.persist(wal)
    payload = wal.get(LEDGER_PATH)
    assert payload and payload["plans"], "persist wrote no plans"
    wal.close()
    reopened = PropertyStore(data_dir=str(tmp_path / "wal"), fsync="off")
    try:
        fresh = PerfLedger()
        assert fresh.restore(reopened) >= 1, \
            "restored zero plans after store restart"
        key = next(iter(payload["plans"]))
        _cur, _ref, w, table = fresh.plan_windows(key)
        assert w > 0 and table == "sentab"
    finally:
        reopened.close()
