"""EXECUTION_ONLY_OPTIONS audit (ISSUE 16 satellite).

``cache/keys.py`` folds every SET option NOT in EXECUTION_ONLY_OPTIONS
into result-cache fingerprints. That is the safe default — but each
execution-only option someone forgets to classify silently splits cache
entries per spelling/value, and each option wrongly classified as
execution-only can serve stale rows. This test enumerates every SET
option the codebase actually reads and fails when one appears that is
in neither the execution-only set nor the deliberately-result-affecting
list below, forcing new options to be classified on introduction.
"""

from __future__ import annotations

import re
from pathlib import Path

from pinot_tpu.cache.keys import EXECUTION_ONLY_OPTIONS

# Options that change WHAT a query returns (or whose effect on returned
# rows is uncertain enough that conservative fingerprint-folding is the
# right call). Each entry is a deliberate decision, not a default:
RESULT_AFFECTING = {
    # response shape/content:
    "analyze",             # EXPLAIN ANALYZE renders a plan table
    "enablenullhandling",  # flips null comparison semantics
    "numgroupslimit",      # changes which groups survive trimming
    "allowpartialresults", # permits responses missing shards
    # conservative (execution strategy, but float reduction order or
    # trim interplay can alter returned cells in the low bits):
    "usefusedkernel",
    "sparsegroupby",
}


def _options_read_in_source() -> set:
    """Every literal SET-option name the engine reads from
    query_options, lowercased."""
    root = Path(__file__).resolve().parent.parent / "pinot_tpu"
    direct = re.compile(r'query_options(?:\.get\(|\[)\s*"([a-zA-Z]+)"')
    # the iterate-and-compare idiom (mse/runtime.py deviceJoin): only
    # counts when query_options is what's being iterated nearby, so
    # header/dict compares elsewhere don't leak in
    compared = re.compile(r'k\.lower\(\)\s*==\s*"([a-z]+)"')
    found = set()
    for p in root.rglob("*.py"):
        text = p.read_text()
        found.update(m.lower() for m in direct.findall(text))
        for m in compared.finditer(text):
            if "query_options" in text[max(0, m.start() - 300):m.start()]:
                found.add(m.group(1).lower())
    return found


def test_every_read_option_is_classified():
    found = _options_read_in_source()
    # sanity: the scanner sees the well-known options, so an empty scan
    # can never masquerade as a clean audit
    assert {"trace", "timeoutms", "segmentcache", "coalesce"} <= found
    unclassified = found - EXECUTION_ONLY_OPTIONS - RESULT_AFFECTING
    assert not unclassified, (
        f"SET option(s) {sorted(unclassified)} read by the engine but "
        "classified neither execution-only (cache/keys.py "
        "EXECUTION_ONLY_OPTIONS) nor deliberately result-affecting "
        "(RESULT_AFFECTING in this test). Decide which and add it.")


def test_classifications_do_not_overlap():
    both = EXECUTION_ONLY_OPTIONS & RESULT_AFFECTING
    assert not both, f"options classified both ways: {sorted(both)}"


def test_execution_only_entries_are_lowercase():
    # the membership check lowercases the query's key; a mixed-case
    # entry here would never match anything
    assert all(o == o.lower() for o in EXECUTION_ONLY_OPTIONS)
    assert all(o == o.lower() for o in RESULT_AFFECTING)


def test_coalesce_is_execution_only():
    """The new knob: coalescing changes HOW (shared dispatch), never
    WHAT — results are bit-identical by construction, so queries with
    and without it share cache entries."""
    assert "coalesce" in EXECUTION_ONLY_OPTIONS
