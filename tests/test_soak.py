"""Short-profile CI runs of the committed soak harness
(pinot_tpu/tools/soak.py) so every reliability-evidence class in the README
is reproducible from a committed entry point.

Reference pattern: ChaosMonkeyIntegrationTest and the H2-oracle
testQueries harness run inside the normal integration-test suite at reduced
scale; the long profiles are the same code with bigger knobs.
"""

from __future__ import annotations

import pytest

from pinot_tpu.tools.soak import (soak_chaos, soak_realtime, soak_rebalance,
                                  soak_sql)


def test_soak_sql_short_profile():
    out = soak_sql(seconds=8.0, seed=7, rows=600, device_parity=False)
    assert out["checks"] >= 20, out


def test_soak_sql_device_parity_short_profile():
    out = soak_sql(seconds=8.0, seed=11, rows=400, device_parity=True,
                   max_checks=60)
    assert out["checks"] >= 10, out


def test_soak_chaos_short_profile():
    out = soak_chaos(seconds=12.0, seed=5, n_servers=3, replication=2,
                     n_segments=4, rows_per_segment=200)
    assert out["queries"] >= 10, out
    # chaos actually happened: at least one kill or rebalance or compaction
    assert out["kills"] + out["rebalances"] + out["compactions"] >= 1, out


@pytest.mark.rebalance
def test_soak_rebalance_short_profile():
    """Elastic-capacity soak at smoke scale, faults armed on the
    ``rebalance.move`` destination-fetch point: server kill/add churn must
    drive the durable actuation loop through at least one completed job
    (dead-server rebuild or server-add spread) while live queries stay
    exact-or-degraded and the end state holds full replication."""
    out = soak_rebalance(seconds=6.0, seed=13, n_segments=6,
                         rows_per_segment=150, fault_rate=0.05)
    assert out["queries"] >= 10, out
    assert out["jobs_done"] >= 1, out
    assert out["server_kills"] + out["server_adds"] >= 1, out
    assert out["moves_completed"] >= 1, out


def test_soak_realtime_one_round():
    out = soak_realtime(rounds=1, seed=3, rows_per_round=40)
    assert out["rounds"] == 1, out


def test_soak_cli_smoke(capsys):
    from pinot_tpu.tools.soak import main
    rc = main(["--suite", "realtime", "--rounds", "1", "--quiet"])
    assert rc == 0
    import json
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["ok"] is True


def test_soak_report_artifact(tmp_path, capsys):
    """--report writes the machine-readable run artifact: per-suite
    results, final per-role metrics snapshots, cost-report aggregates
    from the broker's workload tracker, and the closing anomaly list."""
    import json

    from pinot_tpu.tools.soak import main

    out = tmp_path / "soak_report.json"
    rc = main(["--suite", "chaos", "--seconds", "4", "--quiet",
               "--report", str(out)])
    capsys.readouterr()
    assert rc == 0
    report = json.loads(out.read_text())
    assert report["ok"] is True
    assert report["schemaVersion"] == 1
    assert set(report["metrics"]) == {"server", "broker", "controller"}
    assert report["metrics"]["broker"]["timers"][
        "queryProcessingTimeMs"]["count"] > 0
    # the chaos suite's broker workload rollup made it into the artifact
    assert "stats" in report["costReports"]["chaos"]["tables"]
    assert isinstance(report["anomalies"], list)
    chaos = report["results"][0]
    assert chaos["fleet"]["serversReachable"] >= 1
