"""High-cardinality (sparse, sort-based) group-by: device vs host parity.

The dense cartesian segment_sum table caps at DENSE_GROUP_LIMIT (2^21)
groups; beyond it the planner switches to the sort-based device path
(ops/kernels._run_sparse_group_by) — the TPU analogue of the reference's
hash-map group-key generators with numGroupsLimit trim
(pinot-core/.../groupby/DictionaryBasedGroupKeyGenerator.java:119-137,
InstancePlanMakerImplV2.java:245-270).
"""

from __future__ import annotations

import sqlite3

import numpy as np
import pytest

from pinot_tpu.engine.plan import DENSE_GROUP_LIMIT, SegmentPlanner
from pinot_tpu.engine.query_executor import QueryExecutor
from pinot_tpu.query.parser.sql import parse_sql
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.spi.data_types import Schema

N = 5000
HIGH_CARD = 3000  # ids 0..2999; with code (0..1999) → 6M products > 2^21

SCHEMA = Schema.build(
    "hc",
    dimensions=[("uid", "INT"), ("code", "INT"), ("tag", "STRING")],
    metrics=[("amount", "INT"), ("score", "DOUBLE")])


def _gen(rng, n=N):
    return {
        "uid": rng.integers(0, HIGH_CARD, n).astype(np.int32),
        "code": rng.integers(0, 2000, n).astype(np.int32),
        "tag": np.asarray(["a", "b", "c", "d"], dtype=object)[
            rng.integers(0, 4, n)],
        "amount": rng.integers(-100, 1000, n).astype(np.int32),
        "score": np.round(rng.random(n) * 50, 3),
    }


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    rng = np.random.default_rng(77)
    d = tmp_path_factory.mktemp("hc")
    data = _gen(rng)
    half = N // 2
    segs = []
    for i, sl in enumerate([slice(0, half), slice(half, N)]):
        SegmentBuilder(SCHEMA, segment_name=f"hc_{i}").build(
            {k: v[sl] for k, v in data.items()}, d / f"s{i}")
        segs.append(load_segment(d / f"s{i}"))
    tpu = QueryExecutor(backend="tpu")
    tpu.add_table(SCHEMA, segs)
    host = QueryExecutor(backend="host")
    host.add_table(SCHEMA, segs)

    conn = sqlite3.connect(":memory:")
    conn.execute("CREATE TABLE hc (uid INT, code INT, tag TEXT, "
                 "amount INT, score REAL)")
    for i in range(N):
        conn.execute("INSERT INTO hc VALUES (?,?,?,?,?)",
                     (int(data["uid"][i]), int(data["code"][i]), data["tag"][i],
                      int(data["amount"][i]), float(data["score"][i])))
    return tpu, host, conn, segs


def _rows(resp):
    assert not resp.exceptions, resp.exceptions
    return sorted(map(repr, resp.result_table.rows))


def _check(tpu, host, sql):
    a, b = tpu.execute_sql(sql), host.execute_sql(sql)
    assert _rows(a) == _rows(b), sql
    return a


def test_planner_picks_sparse(env):
    tpu, host, conn, segs = env
    q = parse_sql("SELECT uid, code, SUM(amount) FROM hc "
                  "GROUP BY uid, code LIMIT 100000")
    plan = SegmentPlanner(q, segs[0]).plan()
    assert plan.program.mode == "group_by_sparse"
    card_product = 1
    for dim in plan.group_dims:
        card_product *= dim.cardinality
    assert card_product > DENSE_GROUP_LIMIT


def test_sparse_sum_parity(env):
    tpu, host, conn, segs = env
    _check(tpu, host,
           "SELECT uid, code, SUM(amount), COUNT(*) FROM hc "
           "GROUP BY uid, code LIMIT 100000")


def test_sparse_min_max_avg_parity(env):
    tpu, host, conn, segs = env
    _check(tpu, host,
           "SELECT uid, code, MIN(score), MAX(score), AVG(amount) FROM hc "
           "WHERE tag IN ('a', 'b') GROUP BY uid, code LIMIT 100000")


def test_sparse_three_dims_parity(env):
    tpu, host, conn, segs = env
    _check(tpu, host,
           "SELECT uid, code, tag, SUM(amount) FROM hc "
           "WHERE amount > 0 GROUP BY uid, code, tag LIMIT 100000")


def test_sparse_vs_sqlite(env):
    tpu, host, conn, segs = env
    resp = tpu.execute_sql(
        "SELECT uid, code, SUM(amount) FROM hc GROUP BY uid, code "
        "ORDER BY uid, code LIMIT 100000")
    assert not resp.exceptions, resp.exceptions
    want = conn.execute(
        "SELECT uid, code, SUM(amount) FROM hc GROUP BY uid, code "
        "ORDER BY uid, code").fetchall()
    got = [(int(r[0]), int(r[1]), int(r[2])) for r in resp.result_table.rows]
    assert got == [(int(a), int(b), int(c)) for a, b, c in want]


def test_sparse_distinct(env):
    tpu, host, conn, segs = env
    resp = tpu.execute_sql(
        "SELECT DISTINCT uid, code FROM hc ORDER BY uid, code LIMIT 100000")
    assert not resp.exceptions, resp.exceptions
    want = conn.execute(
        "SELECT DISTINCT uid, code FROM hc ORDER BY uid, code").fetchall()
    got = [(int(r[0]), int(r[1])) for r in resp.result_table.rows]
    assert got == [(int(a), int(b)) for a, b in want]


def test_num_groups_limit_trim(env):
    tpu, host, conn, segs = env
    resp = tpu.execute_sql(
        "SET numGroupsLimit = 50; "
        "SELECT uid, code, SUM(amount) FROM hc GROUP BY uid, code "
        "LIMIT 100000")
    assert not resp.exceptions, resp.exceptions
    # the trim is surfaced, not silent (reference: numGroupsLimitReached)
    assert resp.num_groups_limit_reached
    # trim caps groups per segment; cross-segment merge can reach ≤ 2×limit
    assert 0 < len(resp.result_table.rows) <= 100
    # surviving groups carry exact aggregates (trim drops groups, not rows)
    want = {(int(u), int(c)): int(s) for u, c, s in conn.execute(
        "SELECT uid, code, SUM(amount) FROM hc GROUP BY uid, code")}
    for u, c, s in resp.result_table.rows:
        key = (int(u), int(c))
        # a group surviving in BOTH segments (or present in one) must be
        # exact iff every row of that group landed inside the trim — groups
        # kept by the sort-order trim are complete within each segment
        assert key in want


def test_sparse_derived_dim(env):
    tpu, host, conn, segs = env
    # expression group key (uid remapped through a host LUT) in sparse mode
    _check(tpu, host,
           "SELECT uid + 0, code, SUM(amount) FROM hc "
           "GROUP BY uid + 0, code LIMIT 100000")


def test_sparse_distinctcount_on_device(env):
    """COUNT DISTINCT inside a high-cardinality group-by runs ON DEVICE via
    (group, dictId) pair dedup (VERDICT weak #5) — the planner keeps sparse
    mode instead of rejecting to host."""
    tpu, host, conn, segs = env
    sql = ("SELECT uid, code, DISTINCTCOUNT(tag), SUM(amount) FROM hc "
           "GROUP BY uid, code LIMIT 100000")
    q = parse_sql(sql)
    plan = SegmentPlanner(q, segs[0]).plan()
    assert plan.program.mode == "group_by_sparse"  # device path kept
    resp = _check(tpu, host, sql)
    # numGroupsLimit default exceeds the group count: nothing trimmed
    assert not resp.num_groups_limit_reached
    # sqlite oracle on a sample of groups
    want = {(int(u), int(c)): int(d) for u, c, d in conn.execute(
        "SELECT uid, code, COUNT(DISTINCT tag) FROM hc GROUP BY uid, code")}
    resp = tpu.execute_sql(sql)
    got = {(int(r[0]), int(r[1])): int(r[2]) for r in resp.result_table.rows}
    assert got == want


def test_sparse_distinct_of_wide_value_column(env):
    """Distinct of a WIDE column inside a sparse group-by: pair space =
    6M group keys x ~1100 amounts — the exact occupancy product the dense
    matrix could never hold (VERDICT: 'distinct on a high-card column
    inside a group-by falls off the device path exactly where it
    matters')."""
    tpu, host, conn, segs = env
    sql = ("SELECT uid, code, DISTINCTCOUNT(amount) FROM hc "
           "GROUP BY uid, code LIMIT 100000")
    q = parse_sql(sql)
    plan = SegmentPlanner(q, segs[0]).plan()
    assert plan.program.mode == "group_by_sparse"
    _check(tpu, host, sql)


def test_sparse_unsupported_agg_falls_back(env):
    tpu, host, conn, segs = env
    # PERCENTILE lowers to a value-hist matrix agg → sparse planner
    # rejects, auto backend falls back to host and still answers
    auto = QueryExecutor(backend="auto")
    auto.add_table(SCHEMA, segs)
    sql = ("SELECT uid, code, PERCENTILE(amount, 90) FROM hc "
           "GROUP BY uid, code LIMIT 100000")
    resp = auto.execute_sql(sql)
    assert not resp.exceptions, resp.exceptions
    host_resp = host.execute_sql(sql)
    assert _rows(resp) == _rows(host_resp)


def test_orderby_prefix_trim_pushdown(env):
    """ORDER BY = ASC prefix of the group keys + LIMIT → the kernel only
    allocates offset+limit output slots (the exact-trim pushdown), the
    result still matches sqlite, and the trim is NOT reported as a
    numGroupsLimit event (it cannot change the answer)."""
    tpu, host, conn, segs = env
    sql = ("SELECT uid, code, SUM(amount) FROM hc GROUP BY uid, code "
           "ORDER BY uid, code LIMIT 40")
    q = parse_sql(sql)
    plan = SegmentPlanner(q, segs[0]).plan()
    assert plan.program.mode == "group_by_sparse"
    assert plan.program.num_groups == 40  # not DEFAULT_NUM_GROUPS_LIMIT
    assert plan.program.exact_trim
    resp = tpu.execute_sql(sql)
    assert not resp.exceptions, resp.exceptions
    assert not resp.num_groups_limit_reached
    want = conn.execute(
        "SELECT uid, code, SUM(amount) FROM hc GROUP BY uid, code "
        "ORDER BY uid, code LIMIT 40").fetchall()
    got = [(int(r[0]), int(r[1]), int(r[2])) for r in resp.result_table.rows]
    assert got == [(int(a), int(b), int(c)) for a, b, c in want]
    # a DISTINCTCOUNT (dict-merge path) under the pushdown also stays exact
    sql2 = ("SELECT uid, code, DISTINCTCOUNT(tag), SUM(amount) FROM hc "
            "GROUP BY uid, code ORDER BY uid, code LIMIT 30")
    assert SegmentPlanner(parse_sql(sql2), segs[0]).plan().program.num_groups == 30
    r2 = tpu.execute_sql(sql2)
    assert not r2.exceptions, r2.exceptions
    want2 = conn.execute(
        "SELECT uid, code, COUNT(DISTINCT tag), SUM(amount) FROM hc "
        "GROUP BY uid, code ORDER BY uid, code LIMIT 30").fetchall()
    got2 = [tuple(int(v) for v in r) for r in r2.result_table.rows]
    assert got2 == [tuple(int(v) for v in r) for r in want2]


def test_orderby_trim_not_pushed_when_unsafe(env):
    tpu, host, conn, segs = env
    from pinot_tpu.engine.plan import DEFAULT_NUM_GROUPS_LIMIT

    for sql in [
        # DESC: keep-smallest would be wrong
        "SELECT uid, code, SUM(amount) FROM hc GROUP BY uid, code "
        "ORDER BY uid DESC LIMIT 40",
        # ordered by an aggregate, not a key prefix
        "SELECT uid, code, SUM(amount) FROM hc GROUP BY uid, code "
        "ORDER BY SUM(amount) LIMIT 40",
        # key order swapped: not a prefix in stride order
        "SELECT uid, code, SUM(amount) FROM hc GROUP BY uid, code "
        "ORDER BY code, uid LIMIT 40",
        # partial prefix: exactness would need full-key tie-breaks in the
        # dict-path reduce — not pushed down
        "SELECT uid, code, SUM(amount) FROM hc GROUP BY uid, code "
        "ORDER BY uid LIMIT 40",
        # HAVING may drop groups after trim
        "SELECT uid, code, SUM(amount) FROM hc GROUP BY uid, code "
        "HAVING SUM(amount) > 10 ORDER BY uid, code LIMIT 40",
    ]:
        plan = SegmentPlanner(parse_sql(sql), segs[0]).plan()
        assert plan.program.num_groups == DEFAULT_NUM_GROUPS_LIMIT, sql
        assert not plan.program.exact_trim, sql
        _check(tpu, host, sql)


def test_sparse_float_sum_error_stays_local_to_group(tmp_path):
    """SUM(DOUBLE) rounding must scale with the GROUP's magnitude, not the
    segment's running total: at values ~1e12 over 20K rows the global
    prefix reaches ~2e16 (ulp ≈ 4.0) — a prefix-diff implementation leaks
    that ulp into every small group, while the segmented tree scan keeps
    error near ulp(group sum) ≈ 1e-3."""
    rng = np.random.default_rng(5)
    n = 20000
    data = {
        "uid": rng.integers(0, HIGH_CARD, n).astype(np.int32),
        "code": rng.integers(0, 2000, n).astype(np.int32),
        "tag": np.asarray(["a"] * n, dtype=object),
        "amount": np.zeros(n, np.int32),
        "score": 1e12 + np.round(rng.random(n), 3),
    }
    SegmentBuilder(SCHEMA, segment_name="prec").build(data, tmp_path / "p")
    seg = load_segment(tmp_path / "p")
    tpu = QueryExecutor(backend="tpu")
    tpu.add_table(SCHEMA, [seg])
    q = parse_sql("SELECT uid, code, SUM(score) FROM hc "
                  "GROUP BY uid, code LIMIT 100000")
    assert SegmentPlanner(q, seg).plan().program.mode == "group_by_sparse"
    resp = tpu.execute_sql(
        "SELECT uid, code, SUM(score) FROM hc GROUP BY uid, code LIMIT 100000")
    assert not resp.exceptions, resp.exceptions
    want = {}
    for u, c, s in zip(data["uid"], data["code"], data["score"]):
        want[(int(u), int(c))] = want.get((int(u), int(c)), 0.0) + s
    got = {(int(r[0]), int(r[1])): float(r[2]) for r in resp.result_table.rows}
    assert got.keys() == want.keys()
    worst = max(abs(got[k] - want[k]) for k in want)
    assert worst < 1e-2, f"group sum error {worst} ~ global-total ulp leak"


def test_trim_still_counts_scanned_docs(env):
    tpu, host, conn, segs = env
    full = tpu.execute_sql(
        "SELECT uid, code, SUM(amount) FROM hc GROUP BY uid, code LIMIT 100000")
    trimmed = tpu.execute_sql(
        "SET numGroupsLimit = 50; "
        "SELECT uid, code, SUM(amount) FROM hc GROUP BY uid, code LIMIT 100000")
    assert not full.exceptions and not trimmed.exceptions
    # trimming drops groups from the result but not from docs scanned
    assert trimmed.num_docs_scanned == full.num_docs_scanned == N


def test_medium_reduce_desc_string_and_bool_keys(env):
    """Dict-form intermediates (non-vec aggs) with DESC string keys and
    boolean-ish keys exercise the columnar medium reduce's comparator —
    shapes that numpy argsort would need dtype guards for."""
    tpu, host, conn, segs = env
    for sql in [
        "SELECT tag, DISTINCTCOUNT(code) FROM hc GROUP BY tag "
        "ORDER BY tag DESC LIMIT 10",
        "SELECT tag, DISTINCTCOUNT(code) FROM hc GROUP BY tag "
        "ORDER BY DISTINCTCOUNT(code) DESC, tag LIMIT 10",
    ]:
        _check(tpu, host, sql)
