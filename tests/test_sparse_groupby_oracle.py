"""Oracle matrix for the sparse group-by fast paths (ISSUE 2).

Every cell of {COUNT, SUM, MIN, MAX, DISTINCTCOUNT} ×
{presorted key, shuffled key} × {untrimmed, numGroupsLimit trim} ×
multi-segment is checked against sqlite on the SAME rows, and the
device-side sparse combine is checked bit-for-bit (int aggs) against the
host merge (`SET deviceCombine = false`) — the two merge paths must be
indistinguishable from the result tables.

The test cardinality is tiny (dense-eligible), so every query rides the
`SET sparseGroupBy = true` escape hatch to reach the sparse kernel.
"""

from __future__ import annotations

import sqlite3

import numpy as np
import pytest

from pinot_tpu.engine.plan import SegmentPlanner
from pinot_tpu.engine.query_executor import QueryExecutor
from pinot_tpu.query.parser.sql import parse_sql
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.spi.data_types import Schema

N = 6000
N_KEYS = 300
SCHEMA = Schema.build(
    "okv",
    dimensions=[("k", "INT"), ("d", "INT")],
    metrics=[("v", "LONG")])

FORCE = "SET sparseGroupBy = true; "
MATRIX_SQL = (
    "SELECT k, COUNT(*), SUM(v), MIN(v), MAX(v), DISTINCTCOUNT(d) "
    "FROM okv {where}GROUP BY k ORDER BY k LIMIT 100000")
ORACLE_SQL = (
    "SELECT k, COUNT(*), SUM(v), MIN(v), MAX(v), COUNT(DISTINCT d) "
    "FROM okv {where}GROUP BY k ORDER BY k")


def _build_env(tmp_path_factory, presorted: bool):
    rng = np.random.default_rng(42)
    data = {
        "k": rng.integers(0, N_KEYS, N).astype(np.int32),
        "d": rng.integers(0, 16, N).astype(np.int32),
        "v": rng.integers(-500, 5000, N).astype(np.int64),
    }
    d = tmp_path_factory.mktemp("sorted" if presorted else "shuffled")
    half = N // 2
    segs = []
    for i, sl in enumerate([slice(0, half), slice(half, N)]):
        part = {c: a[sl] for c, a in data.items()}
        if presorted:
            # sortedness is a per-segment metadata property: sorting each
            # slice independently keeps the global multiset identical to
            # the shuffled fixture's
            order = np.argsort(part["k"], kind="stable")
            part = {c: a[order] for c, a in part.items()}
        SegmentBuilder(SCHEMA, segment_name=f"s{i}").build(part, d / f"s{i}")
        segs.append(load_segment(d / f"s{i}"))
    tpu = QueryExecutor(backend="tpu")
    tpu.add_table(SCHEMA, segs)
    conn = sqlite3.connect(":memory:")
    conn.execute("CREATE TABLE okv (k INT, d INT, v INT)")
    conn.executemany("INSERT INTO okv VALUES (?,?,?)", zip(
        map(int, data["k"]), map(int, data["d"]), map(int, data["v"])))
    return tpu, conn, segs


@pytest.fixture(scope="module", params=[True, False],
                ids=["presorted", "shuffled"])
def env(request, tmp_path_factory):
    return (*_build_env(tmp_path_factory, request.param), request.param)


def _int_rows(resp):
    assert not resp.exceptions, resp.exceptions
    return [tuple(int(v) for v in row) for row in resp.result_table.rows]


def test_planner_path_matches_fixture(env):
    tpu, conn, segs, presorted = env
    q = parse_sql(FORCE + MATRIX_SQL.format(where=""))
    for seg in segs:
        p = SegmentPlanner(q, seg).plan().program
        assert p.mode == "group_by_sparse"
        assert p.keys_presorted == presorted


def test_agg_matrix_vs_sqlite(env):
    tpu, conn, segs, presorted = env
    got = _int_rows(tpu.execute_sql(FORCE + MATRIX_SQL.format(where="")))
    want = [tuple(int(v) for v in row)
            for row in conn.execute(ORACLE_SQL.format(where=""))]
    assert got == want


def test_agg_matrix_with_filter_vs_sqlite(env):
    # a filter leaves masked rows INSIDE key runs — the presorted path must
    # skip them via op identities, not by moving rows
    tpu, conn, segs, presorted = env
    got = _int_rows(tpu.execute_sql(
        FORCE + MATRIX_SQL.format(where="WHERE v > 100 AND d < 12 ")))
    want = [tuple(int(v) for v in row) for row in conn.execute(
        ORACLE_SQL.format(where="WHERE v > 100 AND d < 12 "))]
    assert got == want


def test_trimmed_groups_stay_exact(env):
    tpu, conn, segs, presorted = env
    resp = tpu.execute_sql(
        FORCE + "SET numGroupsLimit = 40; " + MATRIX_SQL.format(where=""))
    assert not resp.exceptions, resp.exceptions
    assert resp.num_groups_limit_reached
    got = _int_rows(resp)
    assert 0 < len(got) <= 2 * 40  # per-segment cap; merge can reach 2x
    want = {row[0]: tuple(map(int, row))
            for row in conn.execute(ORACLE_SQL.format(where=""))}
    for row in got:
        # the sort-order trim keeps each surviving group COMPLETE within a
        # segment; a group surviving in both segments is globally exact
        assert row[0] in want
    # the low keys sort first, so the smallest surviving keys are complete
    # in both segments and must match sqlite exactly
    exact = [r for r in got[:40] if r == want[r[0]]]
    assert exact, "trim kept no globally-exact group"


def test_device_combine_bit_identical_to_host_merge(env):
    tpu, conn, segs, presorted = env
    for where in ("", "WHERE v > 100 "):
        sql = MATRIX_SQL.format(where=where)
        dev = tpu.execute_sql(FORCE + sql)
        host = tpu.execute_sql(FORCE + "SET deviceCombine = false; " + sql)
        assert not dev.exceptions and not host.exceptions
        # int aggs: bit-for-bit across the two merge implementations
        assert _int_rows(dev) == _int_rows(host)
        assert dev.num_docs_scanned == host.num_docs_scanned


def test_device_combine_under_trim_matches_host_merge(env):
    tpu, conn, segs, presorted = env
    sql = "SET numGroupsLimit = 40; " + MATRIX_SQL.format(where="")
    dev = tpu.execute_sql(FORCE + sql)
    host = tpu.execute_sql(FORCE + "SET deviceCombine = false; " + sql)
    assert not dev.exceptions and not host.exceptions
    assert _int_rows(dev) == _int_rows(host)
    assert dev.num_groups_limit_reached == host.num_groups_limit_reached


def test_single_agg_cells_vs_sqlite(env):
    # each agg alone (different payload counts route differently: 1 payload
    # sorts (key, payload); >=2 payloads take the iota gather)
    tpu, conn, segs, presorted = env
    for fn, oracle_fn in [("COUNT(*)", "COUNT(*)"), ("SUM(v)", "SUM(v)"),
                          ("MIN(v)", "MIN(v)"), ("MAX(v)", "MAX(v)"),
                          ("DISTINCTCOUNT(d)", "COUNT(DISTINCT d)")]:
        got = _int_rows(tpu.execute_sql(
            FORCE + f"SELECT k, {fn} FROM okv GROUP BY k "
                    "ORDER BY k LIMIT 100000"))
        want = [tuple(int(v) for v in row) for row in conn.execute(
            f"SELECT k, {oracle_fn} FROM okv GROUP BY k ORDER BY k")]
        assert got == want, fn
