"""Perf-structure guards for the sparse group-by fast paths (ISSUE 2).

These tests pin the SHAPE of the compiled program, not its timings, so CI
catches a regression that silently reintroduces the O(n log n) sort or the
full-payload sort without any flaky wall-clock assertions:

  * the presorted path (keys_presorted=True) must compile to a jaxpr with
    ZERO `sort` primitives — the whole point of the fast path;
  * the sort-iota path must sort exactly (sort keys + iota32), never the
    payload columns: the one `sort` eqn carries num_sort_keys + 1 operands
    regardless of how many aggregation payloads ride the query.
"""

from __future__ import annotations

import numpy as np
import pytest

from pinot_tpu.engine.plan import SegmentPlanner
from pinot_tpu.ops.kernels import _run_program_impl
from pinot_tpu.query.parser.sql import parse_sql
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.segment.device_cache import SegmentDeviceView
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.spi.data_types import Schema

SCHEMA = Schema.build(
    "perfguard",
    dimensions=[("k", "INT"), ("d", "INT")],
    metrics=[("v1", "LONG"), ("v2", "LONG")],
)
N = 4096
N_KEYS = 64


def _build(tmp_path, sort_keys: bool):
    rng = np.random.default_rng(7)
    k = rng.integers(0, N_KEYS, N).astype(np.int32)
    if sort_keys:
        k = np.sort(k)
    cols = {
        "k": k,
        "d": rng.integers(0, 8, N).astype(np.int32),
        "v1": rng.integers(0, 1000, N).astype(np.int64),
        "v2": rng.integers(0, 1000, N).astype(np.int64),
    }
    name = "sorted" if sort_keys else "shuffled"
    SegmentBuilder(SCHEMA, segment_name=name).build(cols, str(tmp_path / name))
    return load_segment(str(tmp_path / name))


def _jaxpr_for(segment, sql):
    """Plan the query against the segment and trace the kernel body."""
    import jax

    query = parse_sql(sql)
    plan = SegmentPlanner(query, segment).plan()
    view = SegmentDeviceView(segment)
    arrays = plan.gather_arrays(view)
    params = tuple(p if isinstance(p, (np.ndarray, np.generic))
                   else np.asarray(p) for p in plan.params)

    def fn(arrays, params):
        return _run_program_impl(plan.program, arrays, params,
                                 np.int32(segment.num_docs), view.padded)

    return plan.program, jax.make_jaxpr(fn)(arrays, params)


def _sort_eqns(jaxpr):
    """All `sort` eqns in the jaxpr, recursing into sub-jaxprs."""
    found = []

    def walk(j):
        for eqn in j.eqns:
            if eqn.primitive.name == "sort":
                found.append(eqn)
            for v in eqn.params.values():
                for sub in _subjaxprs(v):
                    walk(sub)

    walk(jaxpr.jaxpr)
    return found


def _subjaxprs(v):
    if hasattr(v, "jaxpr"):  # ClosedJaxpr
        yield v.jaxpr
    elif hasattr(v, "eqns"):  # raw Jaxpr
        yield v
    elif isinstance(v, (list, tuple)):
        for x in v:
            yield from _subjaxprs(x)


# force the sparse kernel on the tiny (dense-eligible) test cardinality
FORCE = "SET sparseGroupBy = true; "


def test_presorted_path_compiles_with_zero_sorts(tmp_path):
    seg = _build(tmp_path, sort_keys=True)
    program, jaxpr = _jaxpr_for(
        seg, FORCE + "SELECT k, SUM(v1), COUNT(*) FROM perfguard "
                     "GROUP BY k LIMIT 1000")
    assert program.mode == "group_by_sparse"
    assert program.keys_presorted
    eqns = _sort_eqns(jaxpr)
    assert eqns == [], (
        f"presorted fast path must not lower any sort primitive, "
        f"found {len(eqns)}")


def test_presorted_detection_requires_sorted_column(tmp_path):
    seg = _build(tmp_path, sort_keys=False)
    program, jaxpr = _jaxpr_for(
        seg, FORCE + "SELECT k, SUM(v1), COUNT(*) FROM perfguard "
                     "GROUP BY k LIMIT 1000")
    assert program.mode == "group_by_sparse"
    assert not program.keys_presorted
    assert len(_sort_eqns(jaxpr)) >= 1


@pytest.mark.parametrize("aggs,num_sort_keys", [
    # 3 payloads (v1, v2, v1) sorted through one iota: key + iota = 2 operands
    ("SUM(v1), SUM(v2), MAX(v1)", 1),
    # distinct ids PACK into the key's low digits here (key_space × card
    # fits int32), so the distinct query still sorts a single packed key
    ("DISTINCTCOUNT(d), SUM(v1), SUM(v2)", 1),
])
def test_sort_iota_gather_sorts_keys_plus_iota_only(tmp_path, aggs,
                                                    num_sort_keys):
    seg = _build(tmp_path, sort_keys=False)
    program, jaxpr = _jaxpr_for(
        seg, FORCE + f"SELECT k, {aggs} FROM perfguard GROUP BY k LIMIT 1000")
    assert program.mode == "group_by_sparse"
    assert not program.keys_presorted
    eqns = _sort_eqns(jaxpr)
    assert len(eqns) == 1, f"expected exactly one sort, got {len(eqns)}"
    got = len(eqns[0].invars)
    want = num_sort_keys + 1  # keys + iota32; payloads gather post-sort
    assert got == want, (
        f"sort carries {got} operands; the sort-iota path must sort only "
        f"{want} (payloads must ride the gather, not the sort)")


def test_single_payload_skips_the_iota(tmp_path):
    # with <2 payloads the extra gather costs more than it saves: the
    # kernel sorts (key, payload) directly — still exactly one sort, but
    # carrying the payload instead of an iota
    seg = _build(tmp_path, sort_keys=False)
    program, jaxpr = _jaxpr_for(
        seg, FORCE + "SELECT k, SUM(v1) FROM perfguard GROUP BY k LIMIT 1000")
    assert program.mode == "group_by_sparse"
    eqns = _sort_eqns(jaxpr)
    assert len(eqns) == 1
    assert len(eqns[0].invars) == 2  # key + the single payload
