"""SQL parser tests (reference parity: pinot-common CalciteSqlCompilerTest)."""

import pytest

from pinot_tpu.query.expressions import ExpressionContext, ExpressionType
from pinot_tpu.query.filter import FilterNodeType, PredicateType
from pinot_tpu.query.parser.sql import SqlParseError, parse_sql


def test_basic_group_by():
    qc = parse_sql("SELECT teamID, SUM(runs) FROM baseballStats GROUP BY teamID")
    assert qc.table_name == "baseballStats"
    assert len(qc.select_expressions) == 2
    assert qc.select_expressions[0].identifier == "teamID"
    agg = qc.select_expressions[1]
    assert agg.function.name == "sum"
    assert agg.function.arguments[0].identifier == "runs"
    assert qc.group_by_expressions[0].identifier == "teamID"
    assert qc.aggregations == [agg]
    assert qc.limit == 10  # default
    assert qc.is_aggregation_query and qc.is_group_by


def test_where_tree():
    qc = parse_sql(
        "SELECT COUNT(*) FROM t WHERE a = 5 AND (b > 2.5 OR c IN ('x','y')) AND d BETWEEN 1 AND 10"
    )
    f = qc.filter
    assert f.type == FilterNodeType.AND
    assert len(f.children) == 3
    p0 = f.children[0].predicate
    assert p0.type == PredicateType.EQ and p0.values == (5,)
    or_node = f.children[1]
    assert or_node.type == FilterNodeType.OR
    assert or_node.children[0].predicate.type == PredicateType.RANGE
    assert or_node.children[0].predicate.lower == 2.5
    assert not or_node.children[0].predicate.lower_inclusive
    assert or_node.children[1].predicate.type == PredicateType.IN
    assert or_node.children[1].predicate.values == ("x", "y")
    p2 = f.children[2].predicate
    assert p2.type == PredicateType.RANGE and p2.lower == 1 and p2.upper == 10


def test_count_star_and_distinct():
    qc = parse_sql("SELECT COUNT(*), COUNT(DISTINCT x) FROM t")
    assert qc.aggregations[0].function.name == "count"
    assert qc.aggregations[0].function.arguments[0].identifier == "*"
    assert qc.aggregations[1].function.name == "distinctcount"


def test_order_limit_offset():
    qc = parse_sql("SELECT a FROM t ORDER BY a DESC, b LIMIT 25 OFFSET 5")
    assert not qc.order_by_expressions[0].ascending
    assert qc.order_by_expressions[1].ascending
    assert qc.limit == 25 and qc.offset == 5
    qc2 = parse_sql("SELECT a FROM t LIMIT 5, 20")
    assert qc2.offset == 5 and qc2.limit == 20


def test_aliases():
    qc = parse_sql("SELECT a AS x, SUM(b) total FROM t GROUP BY a")
    assert qc.aliases == ["x", "total"]


def test_arithmetic_precedence():
    qc = parse_sql("SELECT a + b * 2 FROM t")
    e = qc.select_expressions[0]
    assert e.function.name == "plus"
    assert e.function.arguments[1].function.name == "times"


def test_flipped_comparison():
    qc = parse_sql("SELECT * FROM t WHERE 5 < x")
    p = qc.filter.predicate
    assert p.type == PredicateType.RANGE
    assert p.lower == 5 and not p.lower_inclusive


def test_not_in_like_null():
    qc = parse_sql(
        "SELECT * FROM t WHERE a NOT IN (1,2) AND b LIKE 'foo%' AND c IS NOT NULL AND NOT d = 3"
    )
    kids = qc.filter.children
    assert kids[0].predicate.type == PredicateType.NOT_IN
    assert kids[1].predicate.type == PredicateType.LIKE
    assert kids[2].predicate.type == PredicateType.IS_NOT_NULL
    assert kids[3].type == FilterNodeType.NOT


def test_having_and_options():
    qc = parse_sql(
        "SET useMultistageEngine=true; SELECT a, SUM(b) FROM t GROUP BY a HAVING SUM(b) > 100"
    )
    assert qc.query_options["useMultistageEngine"] is True
    assert qc.having_filter.predicate.type == PredicateType.RANGE
    # HAVING's SUM(b) dedups against select's
    assert len(qc.aggregations) == 1


def test_case_cast_functions():
    qc = parse_sql(
        "SELECT CASE WHEN a > 1 THEN 'hi' ELSE 'lo' END, CAST(b AS DOUBLE), datetrunc('DAY', ts) FROM t"
    )
    assert qc.select_expressions[0].function.name == "case"
    assert qc.select_expressions[1].function.name == "cast"
    assert qc.select_expressions[1].function.arguments[1].literal == "DOUBLE"
    assert qc.select_expressions[2].function.name == "datetrunc"


def test_quoted_identifiers_and_strings():
    qc = parse_sql('SELECT "weird col" FROM t WHERE name = \'O\'\'Brien\'')
    assert qc.select_expressions[0].identifier == "weird col"
    assert qc.filter.predicate.values == ("O'Brien",)


def test_negative_numbers():
    qc = parse_sql("SELECT * FROM t WHERE a > -5 AND b = -2.5")
    assert qc.filter.children[0].predicate.lower == -5
    assert qc.filter.children[1].predicate.values == (-2.5,)


def test_explain():
    qc = parse_sql("EXPLAIN PLAN FOR SELECT * FROM t")
    assert qc.explain


def test_parse_errors():
    with pytest.raises(SqlParseError):
        parse_sql("SELECT FROM t")
    with pytest.raises(SqlParseError):
        parse_sql("SELECT a FROM t WHERE")
    with pytest.raises(SqlParseError):
        parse_sql("SELECT a t")  # missing FROM
    with pytest.raises(SqlParseError):
        parse_sql("SELECT a FROM t LIMIT x")


def test_underscore_function_canonicalization():
    qc = parse_sql("SELECT DISTINCT_COUNT(a), distinct_count_hll(b) FROM t")
    assert qc.aggregations[0].function.name == "distinctcount"
    assert qc.aggregations[1].function.name == "distinctcounthll"


def test_anonymous_derived_table():
    """FROM (subquery) without an alias parses (Calcite allows it); the
    parser synthesizes one."""
    from pinot_tpu.mse.parser import parse_relational

    q = parse_relational(
        "SELECT * FROM (SELECT k, SUM(v) AS s FROM t GROUP BY k) WHERE s > 9")
    assert q is not None
    q2 = parse_relational("SELECT COUNT(*) FROM (SELECT DISTINCT k FROM t)")
    assert q2 is not None
