"""Star-tree: results with the tree must equal results without it.

Reference test model: BaseStarTreeV2Test + ~20 per-aggregation subclasses
(pinot-core/src/test/.../startree/v2/) assert star-tree results == full-scan
results. numDocsScanned must SHRINK with the tree (that's the point).
"""

import numpy as np
import pytest

from pinot_tpu.engine.query_executor import QueryExecutor
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.segment.startree import try_rewrite
from pinot_tpu.query.parser.sql import parse_sql
from pinot_tpu.spi.data_types import Schema
from pinot_tpu.spi.table_config import IndexingConfig, TableConfig

N = 4000


@pytest.fixture(scope="module")
def tables(tmp_path_factory):
    rng = np.random.default_rng(3)
    tmp = tmp_path_factory.mktemp("stsegs")
    schema = Schema.build(
        "sales",
        dimensions=[("country", "STRING"), ("browser", "STRING"), ("gender", "STRING")],
        metrics=[("impressions", "INT"), ("cost", "DOUBLE")],
    )
    tc = TableConfig(
        table_name="sales",
        indexing=IndexingConfig(star_tree_index_configs=[{
            "dimensionsSplitOrder": ["country", "browser", "gender"],
            "functionColumnPairs": [
                "COUNT__*", "SUM__impressions", "SUM__cost",
                "MIN__impressions", "MAX__impressions",
            ],
            "maxLeafRecords": 100,
        }]),
    )
    countries = ["US", "DE", "JP", "IN", "BR"]
    browsers = ["chrome", "firefox", "safari"]
    genders = ["F", "M", "U"]

    def cols(n, seed):
        r = np.random.default_rng(seed)
        return {
            "country": [countries[int(r.integers(5))] for _ in range(n)],
            "browser": [browsers[int(r.integers(3))] for _ in range(n)],
            "gender": [genders[int(r.integers(3))] for _ in range(n)],
            "impressions": [int(r.integers(0, 1000)) for _ in range(n)],
            "cost": [float(np.round(r.random() * 50, 2)) for _ in range(n)],
        }

    with_tree, without_tree = [], []
    for si in range(2):
        d1 = tmp / f"st_{si}"
        SegmentBuilder(schema, table_config=tc, segment_name=f"st_{si}").build(cols(N, si), d1)
        with_tree.append(load_segment(d1))
        d2 = tmp / f"plain_{si}"
        SegmentBuilder(schema, segment_name=f"plain_{si}").build(cols(N, si), d2)
        without_tree.append(load_segment(d2))
    return schema, with_tree, without_tree


QUERIES = [
    "SELECT country, SUM(impressions) FROM sales GROUP BY country",
    "SELECT country, browser, SUM(impressions), COUNT(*), SUM(cost) FROM sales "
    "GROUP BY country, browser LIMIT 100",
    "SELECT SUM(impressions), COUNT(*) FROM sales WHERE country = 'US'",
    "SELECT browser, AVG(cost), MIN(impressions), MAX(impressions) FROM sales "
    "WHERE country IN ('US', 'DE') GROUP BY browser",
    "SELECT gender, MINMAXRANGE(impressions) FROM sales GROUP BY gender",
    "SELECT COUNT(*) FROM sales WHERE country = 'US' AND browser <> 'safari'",
]


@pytest.mark.parametrize("backend", ["tpu", "host"])
@pytest.mark.parametrize("sql", QUERIES)
def test_star_tree_equals_full_scan(tables, backend, sql):
    schema, with_tree, without_tree = tables
    ex_t = QueryExecutor(backend=backend)
    ex_t.add_table(schema, with_tree)
    ex_p = QueryExecutor(backend=backend)
    ex_p.add_table(schema, without_tree)
    rt = ex_t.execute_sql(sql)
    rp = ex_p.execute_sql(sql)
    assert rt.result_table is not None, rt.exceptions
    assert rp.result_table is not None, rp.exceptions
    a = sorted(rt.result_table.rows, key=repr)
    b = sorted(rp.result_table.rows, key=repr)
    assert len(a) == len(b), sql
    for ra, rb in zip(a, b):
        for x, y in zip(ra, rb):
            if isinstance(x, float):
                # pre-aggregation changes float summation order (same as the
                # reference's star-tree) — compare within rounding tolerance
                assert x == pytest.approx(y, rel=1e-12), (sql, ra, rb)
            else:
                assert x == y, (sql, ra, rb)
    # the whole point: fewer docs scanned via pre-aggregation
    assert rt.num_docs_scanned < rp.num_docs_scanned, sql


def test_rewrite_eligibility(tables):
    schema, with_tree, _ = tables
    seg = with_tree[0]
    # eligible
    assert try_rewrite(parse_sql(
        "SELECT country, SUM(impressions) FROM sales GROUP BY country"), seg) is not None
    # filter on non-dim column → not eligible
    assert try_rewrite(parse_sql(
        "SELECT SUM(impressions) FROM sales WHERE cost > 5"), seg) is None
    # unsupported aggregation → not eligible
    assert try_rewrite(parse_sql(
        "SELECT DISTINCTCOUNT(country) FROM sales"), seg) is None
    # MIN on a column without MIN pair → not eligible
    assert try_rewrite(parse_sql(
        "SELECT MIN(cost) FROM sales"), seg) is None
    # selection → not eligible
    assert try_rewrite(parse_sql(
        "SELECT country FROM sales LIMIT 5"), seg) is None


def test_star_tree_disabled_flag(tables):
    schema, with_tree, _ = tables
    ex = QueryExecutor(backend="tpu")
    ex.add_table(schema, with_tree)
    ex.use_star_tree = False
    sql = "SELECT country, SUM(impressions) FROM sales GROUP BY country"
    full = ex.execute_sql(sql)
    ex.use_star_tree = True
    fast = ex.execute_sql(sql)
    assert sorted(map(repr, full.result_table.rows)) == sorted(map(repr, fast.result_table.rows))
    assert fast.num_docs_scanned < full.num_docs_scanned


def test_count_and_avg_share_count_pair(tables):
    # COUNT(*) + AVG(x) dedup onto one sum(__count__star) inner agg
    schema, with_tree, without_tree = tables
    sql = "SELECT country, COUNT(*), AVG(cost) FROM sales GROUP BY country"
    ex_t = QueryExecutor(backend="tpu")
    ex_t.add_table(schema, with_tree)
    ex_p = QueryExecutor(backend="tpu")
    ex_p.add_table(schema, without_tree)
    a = sorted(ex_t.execute_sql(sql).result_table.rows, key=repr)
    b = sorted(ex_p.execute_sql(sql).result_table.rows, key=repr)
    for ra, rb in zip(a, b):
        assert ra[0] == rb[0] and ra[1] == rb[1]
        assert ra[2] == pytest.approx(rb[2], rel=1e-12)
