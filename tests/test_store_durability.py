"""Crash-consistent property store: WAL, snapshots, recovery, fault matrix.

Reference analogue: ZooKeeper transaction log + snapshot durability — the
control-plane state Pinot keeps in ZK (ideal states, segment DONE records,
lineage epochs) must survive controller/process restarts. The matrix here
mirrors PR-8's wire-framing tests at the storage layer: length+crc32 frame
per record, torn tails truncated at the first bad frame, bitflips detected
by CRC — all deterministic from the seed.
"""

from __future__ import annotations

import json
import os
import struct
import zlib

import pytest

from pinot_tpu.cluster import store as store_mod
from pinot_tpu.cluster.store import BadVersionError, PropertyStore, StoreError
from pinot_tpu.spi import faults


@pytest.fixture(autouse=True)
def _reset_faults():
    yield
    faults.FAULTS.reset()


def _reopen(d, **kw):
    return PropertyStore(data_dir=str(d), fsync="off", **kw)


# -- WAL round-trip -----------------------------------------------------------


def test_journal_roundtrip_preserves_values_and_versions(tmp_path):
    s = _reopen(tmp_path)
    s.set("/IDEALSTATES/t", {"seg": {"Server_0": "ONLINE"}})
    s.set("/IDEALSTATES/t", {"seg": {"Server_0": "ONLINE"},
                             "seg2": {"Server_1": "ONLINE"}})
    s.create_if_absent("/CONFIGS/TABLE/t", {"tableName": "t"})
    s.set("/SEGMENTS/t/seg", {"status": "DONE"})
    s.delete("/SEGMENTS/t/seg")
    s.close()

    s2 = _reopen(tmp_path)
    val, version = s2.get_with_version("/IDEALSTATES/t")
    assert val == {"seg": {"Server_0": "ONLINE"},
                   "seg2": {"Server_1": "ONLINE"}}
    assert version == 1  # CAS versions survive the restart
    assert s2.get("/CONFIGS/TABLE/t") == {"tableName": "t"}
    assert s2.get("/SEGMENTS/t/seg") is None
    assert s2.recoveries == 1
    # CAS against the recovered version must behave as before the crash
    s2.set("/IDEALSTATES/t", {}, expected_version=1)
    with pytest.raises(BadVersionError):
        s2.set("/IDEALSTATES/t", {}, expected_version=1)
    s2.close()


def test_ephemeral_entries_never_persisted(tmp_path):
    s = _reopen(tmp_path)
    s.set("/LIVEINSTANCES/Server_0", {"host": "h"},
          ephemeral_owner="Server_0")
    s.create_if_absent("/CONTROLLER/LEADER", {"instance": "c1"},
                       ephemeral_owner="c1")
    s.set("/CONFIGS/TABLE/t", {"tableName": "t"})
    s.close()
    s2 = _reopen(tmp_path)
    assert s2.get("/LIVEINSTANCES/Server_0") is None
    assert s2.get("/CONTROLLER/LEADER") is None
    assert s2.get("/CONFIGS/TABLE/t") == {"tableName": "t"}
    s2.close()


def test_persistent_entry_shadowed_by_ephemeral_is_forgotten(tmp_path):
    """set(ephemeral) over a journaled persistent path must journal a
    delete, or restart would resurrect the stale persistent value."""
    s = _reopen(tmp_path)
    s.set("/X", "persistent")
    s.set("/X", "ephemeral", ephemeral_owner="sess")
    s.close()
    s2 = _reopen(tmp_path)
    assert s2.get("/X") is None
    s2.close()


def test_delete_if_atomic_and_journaled(tmp_path):
    s = _reopen(tmp_path)
    s.set("/L", {"instance": "c1"})
    assert not s.delete_if("/L", lambda v: v.get("instance") == "other")
    assert s.get("/L") == {"instance": "c1"}
    assert s.delete_if("/L", lambda v: v.get("instance") == "c1")
    assert s.get("/L") is None
    assert not s.delete_if("/L", lambda v: True)  # already gone
    s.close()
    s2 = _reopen(tmp_path)
    assert s2.get("/L") is None
    s2.close()


def test_delete_if_notifies_watchers(tmp_path):
    s = PropertyStore()
    events = []
    s.watch("/L", lambda p, v: events.append((p, v)))
    s.set("/L", {"instance": "c1"})
    s.delete_if("/L", lambda v: True)
    assert events == [("/L", {"instance": "c1"}), ("/L", None)]


# -- snapshot + compaction ----------------------------------------------------


def test_snapshot_compaction_and_recovery(tmp_path):
    s = _reopen(tmp_path, snapshot_threshold_bytes=256)
    for i in range(50):
        s.set("/K", {"i": i})
    assert s.snapshots > 0
    assert s.durability_stats()["journalBytes"] < 256
    s.close()
    s2 = _reopen(tmp_path)
    val, version = s2.get_with_version("/K")
    assert val == {"i": 49}
    assert version == 49
    s2.close()


def test_corrupt_snapshot_fails_loudly(tmp_path):
    s = _reopen(tmp_path, snapshot_threshold_bytes=64)
    for i in range(10):
        s.set("/K", {"i": i})
    assert s.snapshots > 0
    s.close()
    snap = tmp_path / "store.snapshot"
    blob = bytearray(snap.read_bytes())
    blob[len(blob) // 2] ^= 0x40
    snap.write_bytes(bytes(blob))
    # snapshot writes are atomic (tmp+replace): damage is real corruption,
    # not a torn tail — guessing at state would be worse than failing
    with pytest.raises(StoreError):
        _reopen(tmp_path)


# -- torn tails and the seeded corruption matrix ------------------------------


def test_torn_tail_truncated_at_first_bad_frame(tmp_path):
    s = _reopen(tmp_path)
    s.set("/A", 1)
    s.set("/B", 2)
    s.close()
    jp = tmp_path / "store.journal"
    good_len = jp.stat().st_size
    with open(jp, "ab") as f:
        f.write(struct.pack("<II", 9999, 0xDEAD))  # header of a torn frame
        f.write(b"\x01\x02")
    s2 = _reopen(tmp_path)
    assert (s2.get("/A"), s2.get("/B")) == (1, 2)
    assert s2.truncations == 1
    assert jp.stat().st_size == good_len  # tail physically truncated
    s2.close()
    s3 = _reopen(tmp_path)  # second recovery is clean
    assert s3.truncations == 0
    s3.close()


@pytest.mark.parametrize("mode", ["bitflip", "truncate"])
@pytest.mark.parametrize("seed", [1, 7, 42])
def test_recovery_matrix_seeded_frame_corruption(tmp_path, mode, seed):
    """Deterministic matrix: corrupt frame k of n with corrupt_bytes(seed);
    recovery keeps exactly the records before k and truncates the rest —
    and two recoveries from identical damage agree bit-for-bit."""
    n = 12
    s = _reopen(tmp_path / "a")
    frames = []
    for i in range(n):
        rec = json.dumps({"op": "set", "path": f"/P/{i}", "value": i,
                          "version": 0}, separators=(",", ":")).encode()
        frames.append(struct.pack("<II", len(rec), zlib.crc32(rec)) + rec)
        s.set(f"/P/{i}", i)
    s.close()

    k = seed % n
    jp = tmp_path / "a" / "store.journal"
    blob = jp.read_bytes()
    off = sum(len(f) for f in frames[:k])
    damaged = faults.corrupt_bytes(blob[off:off + len(frames[k])],
                                   mode=mode, seed=seed, index=k)
    jp.write_bytes(blob[:off] + damaged + blob[off + len(frames[k]):])

    recovered = []
    for _ in range(2):
        s2 = _reopen(tmp_path / "a")
        recovered.append({p: s2.get(p) for p in s2.list_paths("/P")})
        s2.close()
        # restore identical damage for the second pass (the first pass
        # truncated the file)
        jp.write_bytes(blob[:off] + damaged + blob[off + len(frames[k]):])
    assert recovered[0] == recovered[1]
    assert recovered[0] == {f"/P/{i}": i for i in range(k)}


# -- fsync policy -------------------------------------------------------------


def test_fsync_policy_always_vs_off(tmp_path):
    before = store_mod.FSYNC_CALLS
    s = PropertyStore(data_dir=str(tmp_path / "always"), fsync="always")
    s.set("/A", 1)
    s.set("/A", 2)
    assert store_mod.FSYNC_CALLS - before >= 2  # one per append
    s.close()
    before = store_mod.FSYNC_CALLS
    s = PropertyStore(data_dir=str(tmp_path / "off"), fsync="off")
    for i in range(5):
        s.set("/A", i)
    s.close()
    assert store_mod.FSYNC_CALLS == before  # off never fsyncs

    with pytest.raises(StoreError):
        PropertyStore(data_dir=str(tmp_path / "bad"), fsync="bogus")


def test_fsync_policy_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv("PINOT_TPU_STORE_FSYNC", "always")
    s = PropertyStore(data_dir=str(tmp_path))
    assert s.durability_stats()["fsyncPolicy"] == "always"
    s.close()


# -- store.journal fault point ------------------------------------------------


def test_journal_error_fault_is_crash_after_append(tmp_path):
    """An error fault at store.journal models death AFTER the WAL append
    but BEFORE apply/notify: the caller sees a failure, memory is
    unchanged, yet recovery replays the record — the durable outcome wins
    (exactly the idempotency segment commits rely on)."""
    s = _reopen(tmp_path)
    s.set("/A", "before")
    with faults.injected("store.journal", kind="error", times=1):
        with pytest.raises(faults.InjectedFault):
            s.set("/A", "after")
    assert s.get("/A") == "before"  # not applied in memory
    s.close()
    s2 = _reopen(tmp_path)
    assert s2.get("/A") == "after"  # but durably journaled
    s2.close()


def test_journal_corrupt_fault_is_torn_write(tmp_path):
    """A corrupt fault at store.journal damages the on-disk frame while the
    in-memory write proceeds — the torn-write shape. Recovery truncates at
    the damaged frame and keeps everything before it."""
    s = _reopen(tmp_path)
    s.set("/A", 1)
    with faults.injected("store.journal", kind="corrupt", times=1, seed=3):
        s.set("/B", 2)  # acked in memory, torn on disk
    s.set("/C", 3)  # lands after the torn frame — also lost to truncation
    assert (s.get("/A"), s.get("/B"), s.get("/C")) == (1, 2, 3)
    s.close()
    s2 = _reopen(tmp_path)
    assert s2.get("/A") == 1
    assert s2.get("/B") is None
    assert s2.get("/C") is None
    assert s2.truncations == 1
    s2.close()


def test_store_write_fault_fires_before_journal(tmp_path):
    """The pre-existing store.write error fault stays crash-BEFORE-append:
    nothing reaches memory or the journal."""
    s = _reopen(tmp_path)
    with faults.injected("store.write", kind="error", times=1):
        with pytest.raises(faults.InjectedFault):
            s.set("/A", 1)
    assert s.get("/A") is None
    s.close()
    s2 = _reopen(tmp_path)
    assert s2.get("/A") is None
    s2.close()


# -- lineage epoch regression (broker result cache) ---------------------------


def test_cache_epoch_survives_restart(tmp_path):
    """/CACHEEPOCH/{nwt} must survive a controller restart: a reset to 0
    would let the broker result cache serve stale pre-replace results
    keyed on a reused (fingerprint, epoch) pair — bit-for-bit staleness."""
    from pinot_tpu.cache.results import bump_lineage_epoch, lineage_epoch

    s = _reopen(tmp_path)
    for _ in range(3):
        bump_lineage_epoch(s, "stats_OFFLINE")
    epoch = lineage_epoch(s, "stats_OFFLINE")
    assert epoch >= 3
    s.close()
    s2 = _reopen(tmp_path)
    assert lineage_epoch(s2, "stats_OFFLINE") == epoch
    bump_lineage_epoch(s2, "stats_OFFLINE")  # and keeps moving forward
    assert lineage_epoch(s2, "stats_OFFLINE") == epoch + 1
    s2.close()


# -- observability ------------------------------------------------------------


def test_durability_stats_and_journal_bytes_gauge(tmp_path):
    from pinot_tpu.spi.metrics import CONTROLLER_METRICS, ControllerGauge

    s = _reopen(tmp_path)
    s.set("/A", 1)
    stats = s.durability_stats()
    assert stats["durable"] is True
    assert stats["journalBytes"] > 0
    assert stats["fsyncPolicy"] == "off"
    assert CONTROLLER_METRICS.gauge_value(
        ControllerGauge.STORE_JOURNAL_BYTES) == stats["journalBytes"]
    s.close()

    mem = PropertyStore()
    st = mem.durability_stats()
    assert st["durable"] is False and st["journalBytes"] == 0


def test_in_memory_store_unchanged(tmp_path):
    """No data_dir → exactly the old semantics, no journal file anywhere."""
    s = PropertyStore()
    s.set("/A", 1)
    s.set("/A", 2, expected_version=0)
    assert s.get_with_version("/A") == (2, 1)
    assert not os.path.exists(str(tmp_path / "store.journal"))
    s.close()  # harmless no-op
