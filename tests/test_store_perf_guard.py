"""CI perf-structure guard: control-plane durability must be free where
it isn't used.

Same discipline as test_fault_perf_guard.py (call counts, not wall-clock):
with an in-memory store, a warm query must add ZERO journal appends and
ZERO fsyncs — the WAL machinery may not leak into the non-durable path.
With a durable store, the warm query READ path must add zero journal
appends: queries read routing/external-view state, they never write the
store, so durability costs nothing per query. Armed runs then prove the
module-level counters watch the live write path.
"""

from __future__ import annotations

import numpy as np
import pytest

from pinot_tpu.cluster import Broker, ClusterController, ServerInstance
from pinot_tpu.cluster import store as store_mod
from pinot_tpu.cluster.store import PropertyStore
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.spi.data_types import Schema

SQL = "SET resultCache = false; SET segmentCache = false; " \
      "SELECT spk, SUM(spv) FROM storeperf GROUP BY spk"


def _build_cluster(store, d):
    schema = Schema.build("storeperf", dimensions=[("spk", "INT")],
                          metrics=[("spv", "INT")])
    controller = ClusterController(store)
    server = ServerInstance(store, "Server_0", backend="host")
    server.start()
    controller.add_schema(schema.to_json())
    table = controller.create_table({"tableName": "storeperf",
                                     "replication": 1})
    rng = np.random.default_rng(17)
    for i in range(3):
        cols = {"spk": rng.integers(0, 20, 500).astype(np.int32),
                "spv": rng.integers(0, 100, 500).astype(np.int32)}
        SegmentBuilder(schema, segment_name=f"sp_{i}").build(cols, d / f"s{i}")
        controller.add_segment(table, f"sp_{i}",
                               {"location": str(d / f"s{i}"), "numDocs": 500})
    broker = Broker(store)
    for _ in range(2):
        r = broker.execute_sql(SQL)
        assert not r.exceptions, r.exceptions
    return broker, server


@pytest.fixture(scope="module")
def warm_memory_cluster(tmp_path_factory):
    d = tmp_path_factory.mktemp("storeperf_mem")
    broker, server = _build_cluster(PropertyStore(), d)
    yield broker
    server.stop()


@pytest.fixture(scope="module")
def warm_durable_cluster(tmp_path_factory):
    d = tmp_path_factory.mktemp("storeperf_wal")
    store = PropertyStore(data_dir=str(d / "store"), fsync="always")
    broker, server = _build_cluster(store, d)
    yield broker, store
    server.stop()
    store.close()


def test_durability_off_warm_query_zero_journal_cost(warm_memory_cluster):
    appends = store_mod.JOURNAL_APPENDS
    fsyncs = store_mod.FSYNC_CALLS
    r = warm_memory_cluster.execute_sql(SQL)
    assert not r.exceptions, r.exceptions
    assert store_mod.JOURNAL_APPENDS == appends, (
        "an in-memory store must never reach the WAL append path")
    assert store_mod.FSYNC_CALLS == fsyncs, (
        "an in-memory store must never fsync")


def test_durability_on_warm_read_path_zero_store_writes(warm_durable_cluster):
    """Queries only READ the control plane — with fsync=always, a single
    stray store write on the query path would cost a disk flush per query.
    Pin the whole write path to zero."""
    broker, _store = warm_durable_cluster
    appends = store_mod.JOURNAL_APPENDS
    fsyncs = store_mod.FSYNC_CALLS
    for _ in range(3):
        r = broker.execute_sql(SQL)
        assert not r.exceptions, r.exceptions
    assert store_mod.JOURNAL_APPENDS == appends, (
        "warm queries must not write the property store")
    assert store_mod.FSYNC_CALLS == fsyncs, (
        "warm queries must not trigger journal fsyncs")


def test_armed_write_moves_the_counters(warm_durable_cluster):
    """Sanity: the guard watches the live WAL — a real store write must
    append exactly one frame and (fsync=always) exactly one fsync."""
    _broker, store = warm_durable_cluster
    appends = store_mod.JOURNAL_APPENDS
    fsyncs = store_mod.FSYNC_CALLS
    store.set("/perf/guard", {"touch": 1})
    assert store_mod.JOURNAL_APPENDS == appends + 1
    assert store_mod.FSYNC_CALLS == fsyncs + 1


def test_fsync_off_write_appends_without_fsync(tmp_path):
    s = PropertyStore(data_dir=str(tmp_path), fsync="off")
    appends = store_mod.JOURNAL_APPENDS
    fsyncs = store_mod.FSYNC_CALLS
    s.set("/perf/guard", {"touch": 1})
    assert store_mod.JOURNAL_APPENDS == appends + 1
    assert store_mod.FSYNC_CALLS == fsyncs
    s.close()


def test_ephemeral_writes_skip_the_journal(tmp_path):
    """Session-scoped churn (live instances, leader seat) is the hottest
    write class — none of it may touch the WAL."""
    s = PropertyStore(data_dir=str(tmp_path), fsync="always")
    appends = store_mod.JOURNAL_APPENDS
    fsyncs = store_mod.FSYNC_CALLS
    for i in range(5):
        s.set(f"/LIVEINSTANCES/Server_{i}", {"host": "h"},
              ephemeral_owner=f"Server_{i}")
    s.create_if_absent("/CONTROLLER/LEADER", {"instance": "c1"},
                       ephemeral_owner="c1")
    s.expire_session("c1")
    assert store_mod.JOURNAL_APPENDS == appends
    assert store_mod.FSYNC_CALLS == fsyncs
    s.close()
