"""Text, geo, and vector index tests (SURVEY.md §2.2 index inventory)."""

from __future__ import annotations

import numpy as np
import pytest

from pinot_tpu.engine.query_executor import QueryExecutor
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.segment.indexes import (
    GeoGridIndex,
    TextIndex,
    VectorIndex,
    haversine_m,
)
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.spi.data_types import Schema
from pinot_tpu.spi.table_config import IndexingConfig, TableConfig

DOCS = [
    "Apache Pinot is a realtime distributed OLAP datastore",
    "TPU kernels execute fused columnar query plans",
    "the quick brown fox jumps over the lazy dog",
    "realtime ingestion from streaming sources",
    None,
    "distributed query execution with columnar storage",
]


# -- text --------------------------------------------------------------------


def test_text_index_terms_and_bool():
    idx = TextIndex.build(DOCS)
    assert list(idx.docs_for_term("realtime")) == [0, 3]
    assert list(idx.docs_for_term("missing")) == []
    m = idx.mask_match("realtime AND distributed", 6)
    assert list(np.nonzero(m)[0]) == [0]
    m = idx.mask_match("fox OR streaming", 6)
    assert list(np.nonzero(m)[0]) == [2, 3]
    # adjacency = OR (Lucene default)
    m = idx.mask_match("fox streaming", 6)
    assert list(np.nonzero(m)[0]) == [2, 3]


def test_text_index_phrase_and_prefix():
    idx = TextIndex.build(DOCS)
    m = idx.mask_match('"columnar query plans"', 6)
    assert list(np.nonzero(m)[0]) == [1]
    m = idx.mask_match('"query columnar"', 6)  # wrong order: no match
    assert not m.any()
    m = idx.mask_match("stream*", 6)
    assert list(np.nonzero(m)[0]) == [3]
    m = idx.mask_match("(fox OR dog) AND quick", 6)
    assert list(np.nonzero(m)[0]) == [2]


def test_text_match_sql(tmp_path):
    schema = Schema.build("docs", dimensions=[("id", "INT"), ("body", "STRING")])
    cols = {"id": np.arange(len(DOCS), dtype=np.int32),
            "body": np.asarray(["" if d is None else d for d in DOCS], dtype=object)}
    cfg = TableConfig(table_name="docs", indexing=IndexingConfig(
        text_index_columns=["body"]))
    SegmentBuilder(schema, cfg, "d0").build(cols, tmp_path / "d0")
    seg = load_segment(tmp_path / "d0")
    assert seg.get_text_index("body") is not None  # persisted
    for backend in ("host", "tpu"):
        qe = QueryExecutor(backend=backend)
        qe.add_table(schema, [seg])
        r = qe.execute_sql(
            "SELECT id FROM docs WHERE TEXT_MATCH(body, 'columnar AND query') "
            "ORDER BY id LIMIT 10")
        assert not r.exceptions, (backend, r.exceptions)
        assert [x[0] for x in r.result_table.rows] == [1, 5]


# -- geo ---------------------------------------------------------------------


CITIES = {
    "sf": (37.7749, -122.4194),
    "oakland": (37.8044, -122.2712),
    "san_jose": (37.3382, -121.8863),
    "la": (34.0522, -118.2437),
    "nyc": (40.7128, -74.0060),
}


def test_haversine():
    d = haversine_m(*CITIES["sf"], *CITIES["la"])
    assert 540_000 < d < 570_000  # ~559 km
    assert haversine_m(*CITIES["sf"], *CITIES["sf"]) == 0


def test_geo_grid_index():
    names = list(CITIES)
    lat = np.asarray([CITIES[c][0] for c in names])
    lng = np.asarray([CITIES[c][1] for c in names])
    idx = GeoGridIndex.build(lat, lng, res_deg=0.5)
    cand = idx.candidate_docs(*CITIES["sf"], 30_000)
    assert 0 in cand and 1 in cand  # sf + oakland
    assert 4 not in cand  # nyc pruned at candidate stage


def test_geo_sql_query(tmp_path):
    names = list(CITIES)
    schema = Schema.build("places", dimensions=[("name", "STRING")],
                          metrics=[("lat", "DOUBLE"), ("lng", "DOUBLE")])
    cols = {"name": np.asarray(names, dtype=object),
            "lat": np.asarray([CITIES[c][0] for c in names]),
            "lng": np.asarray([CITIES[c][1] for c in names])}
    cfg = TableConfig(table_name="places", indexing=IndexingConfig(
        geo_index_configs=[{"latColumn": "lat", "lngColumn": "lng"}]))
    SegmentBuilder(schema, cfg, "g0").build(cols, tmp_path / "g0")
    seg = load_segment(tmp_path / "g0")
    assert seg.get_geo_index("lat", "lng") is not None
    qe = QueryExecutor(backend="host")
    qe.add_table(schema, [seg])
    r = qe.execute_sql(
        "SELECT name FROM places "
        f"WHERE ST_DISTANCE(lat, lng, {CITIES['sf'][0]}, {CITIES['sf'][1]}) < 30000 "
        "ORDER BY name LIMIT 10")
    assert not r.exceptions, r.exceptions
    assert [x[0] for x in r.result_table.rows] == ["oakland", "sf"]
    # scalar distance in SELECT
    r = qe.execute_sql(
        "SELECT name, ST_DISTANCE(lat, lng, 37.7749, -122.4194) FROM places "
        "WHERE name = 'la'")
    assert 540_000 < r.result_table.rows[0][1] < 570_000


# -- vector ------------------------------------------------------------------


def test_vector_index_exact():
    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(500, 16)).astype(np.float32)
    idx = VectorIndex.build(vecs)
    q = vecs[123]
    docs, sims = idx.top_k(q, 5)
    assert docs[0] == 123
    assert sims[0] == pytest.approx(1.0, abs=1e-5)
    # parity with brute force
    norm = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
    expected = np.argsort(-(norm @ (q / np.linalg.norm(q))))[:5]
    assert set(docs) == set(expected)


def test_vector_index_ivf_recall():
    rng = np.random.default_rng(1)
    # clustered data: IVF probes recover the true cluster
    centers = rng.normal(size=(10, 32)) * 5
    vecs = np.concatenate([c + rng.normal(size=(500, 32)) * 0.3 for c in centers])
    idx = VectorIndex.build(vecs.astype(np.float32), nlist=10)
    assert idx.centroids is not None
    q = vecs[42]
    docs, _ = idx.top_k(q, 10, nprobe=3)
    assert 42 in docs


def test_vector_similarity_sql(tmp_path):
    rng = np.random.default_rng(2)
    dim = 8
    vecs = rng.normal(size=(50, dim)).astype(np.float32)
    schema = Schema.build("emb", dimensions=[("id", "INT"),
                                             ("v", "FLOAT", False)])
    cols = {"id": np.arange(50, dtype=np.int32),
            "v": [list(map(float, row)) for row in vecs]}
    cfg = TableConfig(table_name="emb", indexing=IndexingConfig(
        vector_index_columns=["v"]))
    SegmentBuilder(schema, cfg, "v0").build(cols, tmp_path / "v0")
    seg = load_segment(tmp_path / "v0")
    assert seg.get_vector_index("v") is not None
    qe = QueryExecutor(backend="host")
    qe.add_table(schema, [seg])
    target = ", ".join(f"{x:.6f}" for x in vecs[7])
    r = qe.execute_sql(
        f"SELECT id FROM emb WHERE VECTOR_SIMILARITY(v, ARRAY[{target}], 3) "
        "LIMIT 10")
    assert not r.exceptions, r.exceptions
    ids = [x[0] for x in r.result_table.rows]
    assert 7 in ids and len(ids) == 3


def test_vector_index_survives_serialization(tmp_path):
    rng = np.random.default_rng(3)
    vecs = rng.normal(size=(5000, 8)).astype(np.float32)
    idx = VectorIndex.build(vecs)  # n ≥ 4096 → IVF auto-enabled
    assert idx.centroids is not None
    from pinot_tpu.segment.indexes import (
        deserialize_vector_index,
        serialize_vector_index,
    )

    bufs = {name: np.ascontiguousarray(arr).view(np.uint8)
            for name, arr in serialize_vector_index(idx)}
    back = deserialize_vector_index(bufs)
    q = vecs[99]
    d1, _ = idx.top_k(q, 4)
    d2, _ = back.top_k(q, 4)
    np.testing.assert_array_equal(d1, d2)


def test_geo_antimeridian_wrap():
    """A radius circle crossing ±180° must keep candidates on both sides."""
    lat = np.asarray([0.0, 0.0, 0.0])
    lng = np.asarray([179.9, -179.9, 10.0])
    idx = GeoGridIndex.build(lat, lng, res_deg=0.5)
    cand = idx.candidate_docs(0.0, 179.95, 50_000)  # ~0.45° radius
    assert 0 in cand and 1 in cand
    assert 2 not in cand
    cand = idx.candidate_docs(0.0, -179.95, 50_000)
    assert 0 in cand and 1 in cand


def test_geo_pole_clamp():
    """A circle covering a pole must include all longitudes at that latitude."""
    lat = np.asarray([89.8, 89.8])
    lng = np.asarray([10.0, -170.0])
    idx = GeoGridIndex.build(lat, lng, res_deg=0.5)
    cand = idx.candidate_docs(89.9, 0.0, 60_000)
    assert 0 in cand and 1 in cand


def test_geo_boundary_coordinates():
    """lat=+90 and lng=+180 are storable and findable (grid-edge canon)."""
    lat = np.asarray([90.0, 89.8, 0.0])
    lng = np.asarray([10.0, 10.0, 180.0])
    idx = GeoGridIndex.build(lat, lng, res_deg=0.5)
    cand = idx.candidate_docs(89.9, 10.0, 60_000)
    assert 0 in cand and 1 in cand
    cand = idx.candidate_docs(0.0, -179.95, 50_000)  # 180.0 ≡ -180.0
    assert 2 in cand
