"""Text regex/BM25, vector recall story, pauseless completion.

Reference: native FST regex tests (pinot-segment-local/.../nativefst/),
Lucene BM25 scoring, HNSW recall expectations, and
PauselessSegmentCompletionFSM behavior.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from pinot_tpu.cluster.store import PropertyStore
from pinot_tpu.realtime.completion import SegmentCompletionManager
from pinot_tpu.realtime.manager import RealtimeTableDataManager
from pinot_tpu.segment.indexes import TextIndex, VectorIndex
from pinot_tpu.spi.data_types import Schema
from pinot_tpu.spi.stream import InMemoryStreamRegistry
from pinot_tpu.spi.table_config import (
    IngestionConfig,
    SegmentsValidationConfig,
    TableConfig,
    TableType,
)

DOCS = [
    "quick brown fox jumps over the lazy dog",
    "pack my box with five dozen liquor jugs",
    "the quick onyx goblin jumps over the lazy dwarf",
    "sphinx of black quartz judge my vow",
    "jackdaws love my big sphinx of quartz",
    None,
    "quickest of the quick brown foxes",
]


@pytest.fixture(scope="module")
def text_index():
    return TextIndex.build(DOCS)


def test_regex_term_matching(text_index):
    docs = text_index.docs_for_regex("qu.*")
    assert set(docs) == {0, 2, 3, 4, 6}  # quick/quartz/quickest/...
    docs = text_index.docs_for_regex("jump(s|ed)?")
    assert set(docs) == {0, 2}
    docs = text_index.docs_for_regex("j.ckd.ws")
    assert set(docs) == {4}
    assert len(text_index.docs_for_regex("zzz.*")) == 0
    # TEXT_MATCH syntax: /regex/ terms compose with the boolean operators
    mask = text_index.mask_match("/quick(est)?/ AND fox*", len(DOCS))
    assert set(np.nonzero(mask)[0]) == {0, 6}


def test_bm25_scoring(text_index):
    scores = text_index.bm25_scores("quick", len(DOCS))
    matched = {i for i in range(len(DOCS)) if scores[i] > 0}
    assert matched == {0, 2, 6}
    # doc 6 has "quick" once among 5 tokens; rarer-term docs outrank common
    sphinx = text_index.bm25_scores("sphinx quartz", len(DOCS))
    assert sphinx[3] > 0 and sphinx[4] > 0
    assert sphinx[3] > sphinx[0] == 0.0
    # phrase queries score by their terms
    ph = text_index.bm25_scores('"lazy dog"', len(DOCS))
    assert ph[0] > ph[2] > 0  # doc 0 has both terms, doc 2 only "lazy"


def test_vector_ivf_recall_story(rng):
    """The matmul+IVF design's recall contract: ≥95% recall@10 at the
    default probe width on clustered data (the HNSW-class recall story,
    achieved without pointer chasing)."""
    n, dim, n_clusters = 20_000, 64, 50
    centers = rng.normal(0, 1, (n_clusters, dim))
    data = (centers[rng.integers(0, n_clusters, n)]
            + rng.normal(0, 0.3, (n, dim))).astype(np.float32)
    idx = VectorIndex.build(data)  # nlist auto = sqrt(n)
    assert idx.centroids is not None

    norm = data / np.linalg.norm(data, axis=1, keepdims=True)
    recalls = []
    for _ in range(20):
        q = (centers[rng.integers(0, n_clusters)]
             + rng.normal(0, 0.3, dim)).astype(np.float32)
        qn = q / np.linalg.norm(q)
        exact = set(np.argsort(-(norm @ qn))[:10].tolist())
        approx, _ = idx.top_k(q, 10, nprobe=8)
        recalls.append(len(exact & set(approx.tolist())) / 10)
    assert np.mean(recalls) >= 0.95, np.mean(recalls)


# -- pauseless completion -----------------------------------------------------

SCHEMA = Schema.build(
    "ev", dimensions=[("u", "STRING"), ("ts", "LONG")], metrics=[("n", "INT")])


def _config(topic, flush_rows):
    return TableConfig(
        table_name="ev", table_type=TableType.REALTIME,
        validation=SegmentsValidationConfig(time_column_name="ts"),
        ingestion=IngestionConfig(stream_configs={
            "streamType": "inmemory",
            "stream.inmemory.topic.name": topic,
            "realtime.segment.flush.threshold.rows": flush_rows,
        }))


def wait_until(pred, timeout=20.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.01)
    return False


def test_pauseless_successor_consumes_during_commit(monkeypatch, tmp_path):
    reg = InMemoryStreamRegistry()
    import pinot_tpu.spi.stream as stream_mod

    monkeypatch.setattr(stream_mod, "GLOBAL_STREAM_REGISTRY", reg)
    reg.create_topic("pl", num_partitions=1)
    store = PropertyStore()
    completion = SegmentCompletionManager(store, num_replicas=1,
                                          commit_lease_s=30)
    observed = {"overlap": False}

    def slow_commit(mgr):
        # committer dawdles between build and commitEnd: the successor must
        # already be consuming (ingestion never paused)
        t0 = time.time()
        while time.time() - t0 < 1.0:
            with m._lock:
                if m._committing and m._consuming:
                    observed["overlap"] = True
                    break
            time.sleep(0.01)
        return False  # do not die — just slow

    m = RealtimeTableDataManager(
        SCHEMA, _config("pl", flush_rows=20), tmp_path,
        completion=completion, instance_id="A", pauseless=True,
        test_hooks={"die_before_commit_end": slow_commit})
    m.start()
    try:
        reg.publish("pl", [{"u": f"u{i}", "ts": 1_600_000_000_000 + i,
                            "n": 1} for i in range(25)])
        # while seg 0 commits (slowed), publish more: the successor consumes
        assert wait_until(lambda: m._committing)  # sealed, not committed
        reg.publish("pl", [{"u": f"v{i}", "ts": 1_600_000_100_000 + i,
                            "n": 1} for i in range(10)])
        assert wait_until(
            lambda: sum(s.num_docs for s in m.segments) == 35)
        assert observed["overlap"]  # committing + consuming coexisted
        assert wait_until(lambda: len(m._segment_names) >= 1)
        assert wait_until(lambda: not m._committing)  # commit landed
        # everything stays queryable, exactly once
        assert sum(s.num_docs for s in m.segments) == 35
    finally:
        m.stop()


def test_regex_alternation_and_case(text_index):
    # top-level alternation must not be narrowed to the first branch
    docs = text_index.docs_for_regex("fox(es)?|dog")
    assert set(docs) == {0, 6}
    # uppercase patterns match the lowercased terms
    docs = text_index.docs_for_regex("Quick.*")
    assert set(docs) == {0, 2, 6}
