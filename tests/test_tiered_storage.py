"""Tiered storage: byte-budgeted local segment cache (storage/tier.py),
cold (metadata-only) registrations with first-query lazy warm, the
``storage.fetch`` fault point's corrupt→quarantine→repair-fresh contract,
and the leader-side StoragePrefetcher (storage/prefetch.py).

Reference: Apache Pinot's tiered storage for the cloud (deep store as
the source of truth, servers holding a bounded local working set) and
SegmentFetcherFactory's fetch-through-on-OFFLINE→ONLINE discipline.

Covers: cold replicas advertised ONLINE and warmed by the first query;
evicted segments re-fetched WITH a fresh CRC verify; reader refcounts
(hold/pin) keeping directories alive under eviction and fresh re-fetch;
hot-table pins surviving byte pressure; the warm resident path doing
ZERO disk probes; corrupt and delayed cold fetches degrading loudly
(quarantine+repair / flagged partial) and never caching a partial; the
prefetcher's membership-change-only nudges; and a sub-10s tiered soak
smoke so the full churn loop stays in the tier-1 gate.
"""

from __future__ import annotations

import os
import tarfile
import time
from types import SimpleNamespace

import numpy as np
import pytest

from pinot_tpu.cluster import (Broker, ClusterController, PropertyStore,
                               ServerInstance)
from pinot_tpu.segment import loader as loader_mod
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.spi import faults
from pinot_tpu.spi.data_types import Schema
from pinot_tpu.spi.metrics import SERVER_METRICS, ServerMeter
from pinot_tpu.storage import tier as tier_mod
from pinot_tpu.storage.prefetch import StoragePrefetcher
from pinot_tpu.storage.tier import SegmentTierManager

pytestmark = pytest.mark.tiered

TEAMS = ["BOS", "NYA", "SFN", "LAN"]
GROUP_SQL = ("SELECT team, SUM(runs) FROM {t} GROUP BY team ORDER BY team")

# servers key hosted/cold tables by the type-suffixed internal name
ST = "stats_OFFLINE"
FL = "filler_OFFLINE"


@pytest.fixture(autouse=True)
def _reset_faults():
    yield
    faults.FAULTS.reset()


def _walk_bytes(path) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.stat(os.path.join(root, f)).st_size
            except OSError:
                pass
    return total


def _schema(table: str) -> Schema:
    return Schema.build(table,
                        dimensions=[("team", "STRING"), ("year", "INT")],
                        metrics=[("runs", "INT")])


def _build_tar(tmp, table: str, name: str, seed: int, n: int = 250):
    """Build one segment dir + tarball; returns (tar, extracted_bytes, cols)."""
    rng = np.random.default_rng(seed)
    cols = {
        "team": np.asarray(TEAMS, dtype=object)[rng.integers(0, len(TEAMS), n)],
        "year": rng.integers(2000, 2010, n).astype(np.int32),
        "runs": rng.integers(0, 100, n).astype(np.int32),
    }
    local = tmp / table / name
    SegmentBuilder(_schema(table), segment_name=name).build(cols, local)
    tar = tmp / table / f"{name}.tar.gz"
    with tarfile.open(tar, "w:gz") as tf:
        tf.add(local, arcname=name)
    return str(tar), _walk_bytes(local), cols


def _team_sums(cols_list) -> list:
    agg: dict = {}
    for cols in cols_list:
        for team, runs in zip(cols["team"], cols["runs"]):
            agg[team] = agg.get(team, 0) + int(runs)
    return [(t, agg[t]) for t in sorted(agg)]


def _rows(resp) -> list:
    return [(r[0], int(r[1])) for r in resp.result_table.rows]


def _full(resp) -> bool:
    return not resp.exceptions and not getattr(resp, "partial_result", False)


def _wait(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _cold_stats_cluster(tmp_path, stats_segs=1, filler_segs=1, n=250,
                        extra=0.5):
    """One server whose budget fits the ``stats`` table plus ``extra``
    segment-widths of slack. ``stats`` is registered FIRST (loads
    resident), then ``filler`` — whose eager loads evict the now-LRU
    stats entries. Deterministic end state: every stats segment cold
    (metadata-only, still advertised ONLINE), filler resident."""
    store = PropertyStore()
    controller = ClusterController(store, instance_id="ctl1")
    stats_names, stats_cols, max_seg = [], [], 0
    stats_tars, stats_bytes = [], 0
    for i in range(stats_segs):
        name = f"s{i}"
        tar, nbytes, cols = _build_tar(tmp_path, "stats", name, seed=i, n=n)
        stats_names.append(name)
        stats_cols.append(cols)
        stats_tars.append((name, tar))
        stats_bytes += nbytes
        max_seg = max(max_seg, nbytes)
    filler_names, filler_tars, filler_cols = [], [], []
    for i in range(filler_segs):
        name = f"f{i}"
        tar, nbytes, cols = _build_tar(tmp_path, "filler", name,
                                       seed=100 + i, n=n)
        filler_names.append(name)
        filler_tars.append((name, tar))
        filler_cols.append(cols)
        max_seg = max(max_seg, nbytes)
    budget_bytes = stats_bytes + int(extra * max_seg)
    server = ServerInstance(store, "S0", backend="host",
                            local_storage_mb=budget_bytes / (1024 * 1024))
    server.start()
    broker = Broker(store)
    controller.add_schema(_schema("stats").to_json())
    controller.add_schema(_schema("filler").to_json())
    h_stats = controller.create_table({"tableName": "stats",
                                       "replication": 1})
    h_filler = controller.create_table({"tableName": "filler",
                                        "replication": 1})
    for name, tar in stats_tars:
        controller.add_segment(h_stats, name, {"location": tar, "numDocs": n})
    _wait(lambda: sorted(server.debug_storage()["tables"]
                         .get(ST, {}).get("resident", [])) == stats_names,
          msg="stats resident")
    for name, tar in filler_tars:
        controller.add_segment(h_filler, name, {"location": tar, "numDocs": n})
    _wait(lambda: (
        sorted(server.debug_storage()["tables"]
               .get(ST, {}).get("cold", [])) == stats_names
        and sorted(server.debug_storage()["tables"]
                   .get(FL, {}).get("resident", [])) == filler_names),
        msg="stats demoted cold / filler resident")
    return SimpleNamespace(
        store=store, controller=controller, server=server, broker=broker,
        stats_names=stats_names, filler_names=filler_names,
        stats_cols=stats_cols, filler_cols=filler_cols,
        budget_bytes=budget_bytes, max_seg=max_seg)


# -- cluster: cold registration, lazy warm, evict/re-fetch --------------------


def test_cold_replica_routes_and_first_query_warms(tmp_path):
    c = _cold_stats_cluster(tmp_path, stats_segs=2, filler_segs=2)
    try:
        # cold replicas are still advertised ONLINE (metadata-only routing)
        view = c.store.get(f"/EXTERNALVIEW/{ST}") or {}
        assert sorted(view) == c.stats_names
        for seg in c.stats_names:
            assert view[seg].get("S0") == "ONLINE"
        dbg = c.server.debug_storage()
        assert dbg["coldSegments"] == 2
        assert dbg["residentSegments"] == 2
        assert sorted(dbg["warming"]) == []
        for key in ("budgetBytes", "bytesUsed", "residentDirs", "evictions",
                    "fetches", "pendingRelease", "tierProbes"):
            assert key in dbg["localTier"], key

        cold0 = SERVER_METRICS.meter_count(ServerMeter.SEGMENT_COLD_LOADS)
        evict0 = SERVER_METRICS.meter_count(ServerMeter.SEGMENT_EVICTIONS)
        verify0 = loader_mod.VERIFY_CALLS
        resp = c.broker.execute_sql(
            "SET resultCache=false; " + GROUP_SQL.format(t="stats"))
        assert _full(resp), resp.exceptions
        assert _rows(resp) == _team_sums(c.stats_cols)
        # the query lazily warmed both cold stats segments (re-verifying
        # their CRCs on the way in) and pushed filler out to make room
        assert SERVER_METRICS.meter_count(
            ServerMeter.SEGMENT_COLD_LOADS) - cold0 >= 2
        assert SERVER_METRICS.meter_count(
            ServerMeter.SEGMENT_EVICTIONS) - evict0 >= 2
        assert loader_mod.VERIFY_CALLS - verify0 >= 2
        for seg in c.stats_names:
            assert c.server._tier.resident(ST, seg)
        # disk never exceeded budget + one in-flight fetch
        st = c.server._tier.stats()
        assert st["bytesUsed"] <= c.budget_bytes + c.max_seg
    finally:
        c.server.stop()


def test_evict_refetch_ping_pong_stays_exact(tmp_path):
    """Alternate strict queries between two tables that cannot both fit:
    every round re-fetches evicted segments and must stay bit-identical —
    evict → cold → re-fetchable, never evict → gone."""
    c = _cold_stats_cluster(tmp_path, stats_segs=2, filler_segs=2)
    want_stats = _team_sums(c.stats_cols)
    want_filler = _team_sums(c.filler_cols)
    try:
        evict0 = SERVER_METRICS.meter_count(ServerMeter.SEGMENT_EVICTIONS)
        for _round in range(2):
            for table, want in (("stats", want_stats),
                                ("filler", want_filler)):
                resp = c.broker.execute_sql(
                    "SET resultCache=false; " + GROUP_SQL.format(t=table))
                assert _full(resp), (table, resp.exceptions)
                assert _rows(resp) == want, table
        assert SERVER_METRICS.meter_count(
            ServerMeter.SEGMENT_EVICTIONS) - evict0 >= 4
        st = c.server._tier.stats()
        assert st["bytesUsed"] <= c.budget_bytes + c.max_seg
    finally:
        c.server.stop()


def test_warm_resident_path_zero_disk_probes(tmp_path):
    """Once a table is resident, repeat queries touch the tier only in
    memory: TIER_PROBES (fetch/size-walk/rmtree counter) and CRC verify
    calls must not move at all."""
    store = PropertyStore()
    controller = ClusterController(store, instance_id="ctl1")
    tars, cols_list = [], []
    for i in range(2):
        tar, _nbytes, cols = _build_tar(tmp_path, "stats", f"s{i}", seed=i)
        tars.append((f"s{i}", tar))
        cols_list.append(cols)
    server = ServerInstance(store, "S0", backend="host",
                            local_storage_mb=100.0)
    server.start()
    broker = Broker(store)
    controller.add_schema(_schema("stats").to_json())
    handle = controller.create_table({"tableName": "stats", "replication": 1})
    for name, tar in tars:
        controller.add_segment(handle, name, {"location": tar, "numDocs": 250})
    try:
        sql = "SET resultCache=false; " + GROUP_SQL.format(t="stats")
        resp = broker.execute_sql(sql)
        assert _full(resp) and _rows(resp) == _team_sums(cols_list)
        probes0 = tier_mod.TIER_PROBES
        verify0 = loader_mod.VERIFY_CALLS
        for _ in range(3):
            resp = broker.execute_sql(sql)
            assert _full(resp) and _rows(resp) == _team_sums(cols_list)
        assert tier_mod.TIER_PROBES == probes0
        assert loader_mod.VERIFY_CALLS == verify0
    finally:
        server.stop()


# -- cluster: storage.fetch fault point ---------------------------------------


def test_cold_fetch_corruption_quarantines_then_repairs(tmp_path):
    """A corrupt cold fetch follows the rebalance.move contract: the
    replica quarantines (never served), auto-repair re-fetches a FRESH
    copy, and the next strict query is exact."""
    c = _cold_stats_cluster(tmp_path, stats_segs=1, filler_segs=1)
    want = _team_sums(c.stats_cols)
    try:
        q0 = SERVER_METRICS.meter_count(ServerMeter.SEGMENTS_QUARANTINED)
        r0 = SERVER_METRICS.meter_count(ServerMeter.SEGMENT_REPAIRS)
        faults.FAULTS.arm("storage.fetch", kind="corrupt", times=1)
        # first touch races quarantine+repair: may degrade, never lie
        resp = c.broker.execute_sql(
            "SET allowPartialResults=true; SET resultCache=false; "
            + GROUP_SQL.format(t="stats"))
        if _full(resp):
            assert _rows(resp) == want
        assert faults.FAULTS.fired("storage.fetch") == 1
        _wait(lambda: SERVER_METRICS.meter_count(
            ServerMeter.SEGMENTS_QUARANTINED) > q0, msg="quarantine")
        _wait(lambda: c.server.debug_storage()["tables"]
              .get(ST, {}).get("resident", []) == c.stats_names,
              msg="repair re-fetch")
        assert SERVER_METRICS.meter_count(
            ServerMeter.SEGMENT_REPAIRS) - r0 >= 1
        resp = c.broker.execute_sql(
            "SET resultCache=false; " + GROUP_SQL.format(t="stats"))
        assert _full(resp), resp.exceptions
        assert _rows(resp) == want
        # satellite check: converge eager loads, the cold warm attempt and
        # the repair's fresh copy all went through ONE tier (its fetch
        # counter saw every download)
        assert c.server._tier.stats()["fetches"] >= 4
    finally:
        c.server.stop()


def test_delayed_cold_fetch_degrades_and_partial_is_never_cached(tmp_path):
    """A slow deep store + tight timeoutMs yields a FLAGGED partial
    (coldSegmentsWarming in the response) and the result cache must not
    remember it: the re-issued identical query returns full exact rows."""
    c = _cold_stats_cluster(tmp_path, stats_segs=1, filler_segs=1)
    want = _team_sums(c.stats_cols)
    try:
        faults.FAULTS.arm("storage.fetch", kind="delay", times=1,
                          delay_s=0.6)
        sql = ("SET allowPartialResults=true; SET timeoutMs=150; "
               + GROUP_SQL.format(t="stats"))
        resp = c.broker.execute_sql(sql)  # result cache stays ON
        assert getattr(resp, "partial_result", False)
        assert getattr(resp, "cold_segments_warming", 0) >= 1
        _wait(lambda: c.server.debug_storage()["tables"]
              .get(ST, {}).get("resident", []) == c.stats_names,
              msg="background warm finishing")
        resp = c.broker.execute_sql(sql)
        assert _full(resp), resp.exceptions
        assert _rows(resp) == want
    finally:
        c.server.stop()


# -- cluster: workload-driven prefetch ----------------------------------------


def test_prefetcher_nudges_hot_table_warm(tmp_path):
    c = _cold_stats_cluster(tmp_path, stats_segs=1, filler_segs=1)
    try:
        hits0 = SERVER_METRICS.meter_count(ServerMeter.PREFETCH_HITS)
        c.store.set("/BROKERSTATE/Broker_pf",
                    {"tableCostsMs": {"stats": 42.0}})
        pf = StoragePrefetcher(c.store)
        out = pf()
        assert "stats" in out["nudged"]
        assert c.store.get("/PREFETCH/stats") is not None
        # the server's /PREFETCH watch marks the table hot and warms it
        # in the background — before any query lands
        _wait(lambda: c.server.debug_storage()["tables"]
              .get(ST, {}).get("resident", []) == c.stats_names,
              msg="prefetch warm")
        _wait(lambda: SERVER_METRICS.meter_count(
            ServerMeter.PREFETCH_HITS) > hits0, msg="prefetch hit meter")
        assert "stats" in c.server._tier.stats()["hotTables"]
        # nudges fire on hot-set ENTRY only: a second tick with the same
        # beacons is silent
        assert pf()["nudged"] == []
        resp = c.broker.execute_sql(
            "SET resultCache=false; " + GROUP_SQL.format(t="stats"))
        assert _full(resp) and _rows(resp) == _team_sums(c.stats_cols)
    finally:
        c.server.stop()


# -- tier unit: refcount lifecycle --------------------------------------------


def _unit_tar(tmp_path, table: str, name: str, seed: int):
    tar, nbytes, _cols = _build_tar(tmp_path, table, name, seed, n=120)
    return tar, nbytes


def test_tier_hold_and_zombie_refcounts(tmp_path):
    """acquire(hold=True) protects the fetch→load window; a fresh
    re-fetch retires the old copy as a zombie that survives until its
    readers drain — no ENOENT under a pinned scan, ever."""
    tar_a, nbytes = _unit_tar(tmp_path, "t", "a", seed=1)
    tier = SegmentTierManager("unit0",
                              budget_mb=1.5 * nbytes / (1024 * 1024))
    try:
        path1 = tier.acquire("t", "a", tar_a, hold=True)
        assert os.path.isdir(path1)
        tier.release("t", "a")
        assert tier.resident("t", "a")
        handles = tier.pin("t", ["a"])
        assert len(handles) == 1
        # repair-style fresh re-fetch while a reader is on the old copy
        path2 = tier.acquire("t", "a", tar_a, fresh=True, hold=True)
        assert path2 != path1
        assert os.path.isdir(path1) and os.path.isdir(path2)
        st = tier.stats()
        assert st["pendingRelease"] == 1
        assert st["bytesUsed"] == nbytes  # zombie bytes accounted separately
        assert st["pendingReleaseBytes"] == nbytes
        tier.release("t", "a")            # drops the NEW copy's load ref
        assert tier.resident("t", "a") and os.path.isdir(path1)
        tier.unpin(handles)               # last reader off the zombie
        assert not os.path.isdir(path1)
        assert tier.stats()["pendingRelease"] == 0
        # releasing with no ref outstanding is a no-op, never negative
        tier.release("t", "a")
        assert tier.resident("t", "a")
    finally:
        tier.close()


def test_tier_budget_smaller_than_one_segment_still_loads(tmp_path):
    """The held load ref means a budget below one segment width degrades
    to single-slot churn instead of self-evicting the copy being loaded
    (which would ENOENT every fetch forever)."""
    tar_a, nbytes = _unit_tar(tmp_path, "t", "a", seed=1)
    tar_b, _ = _unit_tar(tmp_path, "t", "b", seed=2)
    tier = SegmentTierManager("unit1",
                              budget_mb=0.5 * nbytes / (1024 * 1024))
    evicted = []
    tier.evict_cb = lambda table, seg: evicted.append((table, seg))
    try:
        path_a = tier.acquire("t", "a", tar_a, hold=True)
        assert os.path.isdir(path_a)      # over budget, but held by loader
        tier.release("t", "a")
        assert tier.resident("t", "a")    # release alone never evicts
        path_b = tier.acquire("t", "b", tar_b, hold=True)
        assert os.path.isdir(path_b)
        assert not tier.resident("t", "a")  # LRU slot handed over
        assert ("t", "a") in evicted
        tier.release("t", "b")
        assert tier.stats()["residentDirs"] == 1
    finally:
        tier.close()


def test_tier_pinned_table_survives_pressure(tmp_path):
    """Explicitly pinned tables are evicted only as a last resort: under
    repeated byte pressure the victims are always the cool tables."""
    tar_a, nbytes = _unit_tar(tmp_path, "A", "a", seed=1)
    tar_b, _ = _unit_tar(tmp_path, "B", "b", seed=2)
    tar_c, _ = _unit_tar(tmp_path, "C", "c", seed=3)
    tier = SegmentTierManager("unit2",
                              budget_mb=2.5 * nbytes / (1024 * 1024))
    try:
        tier.acquire("A", "a", tar_a, hold=True)
        tier.release("A", "a")
        tier.pin_table("A")
        tier.acquire("B", "b", tar_b, hold=True)
        tier.release("B", "b")            # A+B fit: no eviction yet
        assert tier.stats()["evictions"] == 0
        tier.acquire("C", "c", tar_c, hold=True)
        tier.release("C", "c")            # pressure: cool B goes, not A
        assert tier.resident("A", "a")
        assert not tier.resident("B", "b")
        tier.acquire("B", "b", tar_b, hold=True)
        tier.release("B", "b")            # pressure again: C goes, not A
        assert tier.resident("A", "a")
        assert not tier.resident("C", "c")
        assert tier.stats()["evictions"] == 2
        assert tier.stats()["pinnedTables"] == ["A"]
        tier.unpin_table("A")
        assert tier.stats()["pinnedTables"] == []
    finally:
        tier.close()


# -- soak smoke (tier-1) ------------------------------------------------------


def test_tiered_soak_smoke():
    """The full churn loop — tarred deep store, budgeted servers, mixed
    query shapes racing cold warms, disk-bound checks, final strict
    bit-identical pass — at a size that stays well under 10 seconds."""
    from pinot_tpu.tools.soak import soak_tiered

    # 4 tables across 2 budgeted servers: each server hosts ~2 tables of
    # bytes against a 1.2-table budget, so the run must churn
    res = soak_tiered(seconds=0.5, seed=1, n_tables=4,
                      segments_per_table=2, rows_per_segment=120)
    assert res["exact"] > 0
    assert res["cold_loads"] > 0 and res["evictions"] > 0
    assert res["final_checks"] == 16
    assert res["max_tier_bytes_used"] > 0
