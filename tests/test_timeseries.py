"""Timeseries engine tests (reference: pinot-timeseries SPI + m3ql plugin)."""

from __future__ import annotations

import numpy as np
import pytest

from pinot_tpu.engine.query_executor import QueryExecutor
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.spi.data_types import Schema
from pinot_tpu.timeseries import TimeSeriesEngine
from pinot_tpu.timeseries.engine import TimeSeriesQueryError, parse_m3ql

SCHEMA = Schema.build(
    "reqs",
    dimensions=[("svc", "STRING"), ("dc", "STRING")],
    metrics=[("lat", "DOUBLE")],
    date_times=[("ts", "LONG")])


@pytest.fixture(scope="module")
def engine(tmp_path_factory):
    d = tmp_path_factory.mktemp("ts")
    rows = []
    # 2 services × 2 dcs × buckets of 10 at ts 0..39
    for t in range(0, 40):
        for svc in ("api", "web"):
            for dc in ("east", "west"):
                rows.append({"svc": svc, "dc": dc, "ts": t,
                             "lat": 1.0 if svc == "api" else 2.0})
    SegmentBuilder(SCHEMA, segment_name="ts0").build_from_rows(rows, d / "s0")
    qe = QueryExecutor(backend="host")
    qe.add_table(SCHEMA, [load_segment(d / "s0")])
    return TimeSeriesEngine(qe)


def test_parse_m3ql():
    plan = parse_m3ql(
        'fetch table=reqs value=lat time_col=ts filter="svc = \'api\'" '
        "| sum svc,dc | rate | scale 2")
    assert plan.fetch.table == "reqs"
    assert plan.fetch.group_tags == ["svc", "dc"]
    assert [s.name for s in plan.stages] == ["aggregate_tags", "rate", "scale"]


def test_fetch_sum_by_tag(engine):
    block = engine.execute("fetch table=reqs value=lat time_col=ts | sum svc",
                           start=0, end=40, step=10)
    assert block.buckets.num_buckets == 4
    by_tag = {s.label(): s.values for s in block.series}
    # api: 1.0 × 2 dcs × 10 ts per bucket = 20; web: 2.0 × 20 = 40
    assert list(by_tag["svc=api"]) == [20.0] * 4
    assert list(by_tag["svc=web"]) == [40.0] * 4


def test_fetch_filter_and_global_sum(engine):
    block = engine.execute(
        "fetch table=reqs value=lat time_col=ts filter=\"dc = 'east'\" | sum",
        start=0, end=40, step=10)
    assert len(block.series) == 1
    assert list(block.series[0].values) == [30.0] * 4  # (1+2) × 10 per bucket


def test_avg_and_count(engine):
    block = engine.execute("fetch table=reqs value=lat time_col=ts agg=avg | avg svc",
                           start=0, end=40, step=10)
    by_tag = {s.label(): s.values for s in block.series}
    assert list(by_tag["svc=api"]) == [1.0] * 4
    assert list(by_tag["svc=web"]) == [2.0] * 4


def test_pipe_combinators(engine):
    block = engine.execute(
        "fetch table=reqs value=lat time_col=ts | sum | scale 0.5",
        start=0, end=40, step=10)
    assert list(block.series[0].values) == [30.0] * 4  # 60 × 0.5

    block = engine.execute(
        "fetch table=reqs value=lat time_col=ts | sum | rate",
        start=0, end=40, step=10)
    v = block.series[0].values
    assert np.isnan(v[0]) and list(v[1:]) == [0.0, 0.0, 0.0]

    block = engine.execute(
        "fetch table=reqs value=lat time_col=ts | sum | shift 1",
        start=0, end=40, step=10)
    v = block.series[0].values
    assert np.isnan(v[0]) and list(v[1:]) == [60.0] * 3


def test_transform_null_and_sparse(engine):
    # query beyond the data range: empty buckets are NaN then filled
    block = engine.execute(
        "fetch table=reqs value=lat time_col=ts | sum | transform_null 0",
        start=0, end=80, step=10)
    v = block.series[0].values
    assert list(v) == [60.0] * 4 + [0.0] * 4


def test_topk(engine):
    block = engine.execute(
        "fetch table=reqs value=lat time_col=ts | sum svc,dc | topk 2",
        start=0, end=40, step=10)
    assert len(block.series) == 2
    assert all(s.tags["svc"] == "web" for s in block.series)


def test_moving_avg_and_keep_last(engine):
    block = engine.execute(
        "fetch table=reqs value=lat time_col=ts | sum | moving_avg 2",
        start=0, end=40, step=10)
    assert list(block.series[0].values) == [60.0] * 4

    block = engine.execute(
        "fetch table=reqs value=lat time_col=ts | sum | keep_last_value",
        start=0, end=80, step=10)
    assert list(block.series[0].values) == [60.0] * 8


def test_json_shape(engine):
    block = engine.execute("fetch table=reqs value=lat time_col=ts | sum svc",
                           start=0, end=40, step=10)
    j = block.to_json()
    assert j["timeBuckets"] == {"start": 0, "step": 10, "numBuckets": 4}
    assert len(j["series"]) == 2


def test_errors(engine):
    with pytest.raises(TimeSeriesQueryError, match="must start with 'fetch'"):
        engine.execute("sum svc", 0, 10, 1)
    with pytest.raises(TimeSeriesQueryError, match="missing required"):
        engine.execute("fetch table=reqs", 0, 10, 1)
    with pytest.raises(TimeSeriesQueryError, match="unknown pipe stage"):
        engine.execute("fetch table=reqs value=lat time_col=ts | frobnicate",
                       0, 10, 1)
