"""CI perf-structure guard: tracing OFF must cost nothing on the hot path.

Call-count instrumentation, not wall-clock, so it can't flake: after the
query is warm (compile guard satisfied, fused validation settled, planes
resident in HBM), an untraced run must perform ZERO extra
``jax.block_until_ready`` / ``jax.device_get`` calls and allocate ZERO
trace spans — the only tracing cost allowed is the single thread-local
read in ``TRACING.scope``/``active_trace``. A traced run of the same query
is then required to increment both counters, proving the guard actually
watches the instrumented sites.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from pinot_tpu.engine.query_executor import QueryExecutor
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.spi.data_types import Schema
from pinot_tpu.spi.trace import span_allocations

SQL = "SELECT pgk, SUM(pgv) FROM perfguard GROUP BY pgk"


@pytest.fixture(scope="module")
def warm_engine(tmp_path_factory):
    d = tmp_path_factory.mktemp("perfguard")
    # unique column names -> fresh Program -> this module owns its own
    # compile-guard entries regardless of what other tests compiled
    schema = Schema.build("perfguard", dimensions=[("pgk", "INT")],
                         metrics=[("pgv", "INT")])
    rng = np.random.default_rng(7)
    segs = []
    for i in range(4):
        cols = {"pgk": rng.integers(0, 20, 2000).astype(np.int32),
                "pgv": rng.integers(0, 100, 2000).astype(np.int32)}
        SegmentBuilder(schema, segment_name=f"pg_{i}").build(cols, d / f"s{i}")
        segs.append(load_segment(d / f"s{i}"))
    qe = QueryExecutor()
    qe.add_table(schema, segs)
    # warm: first run compiles, second proves the steady state
    for _ in range(2):
        r = qe.execute_sql(SQL)
        assert not r.exceptions, r.exceptions
    return qe


class _CountingSync:
    """Counting wrappers over jax's host-sync entry points."""

    def __init__(self, monkeypatch):
        self.block_calls = 0
        self.device_get_calls = 0
        real_block = jax.block_until_ready
        real_get = jax.device_get

        def counting_block(x):
            self.block_calls += 1
            return real_block(x)

        def counting_get(x):
            self.device_get_calls += 1
            return real_get(x)

        monkeypatch.setattr(jax, "block_until_ready", counting_block)
        monkeypatch.setattr(jax, "device_get", counting_get)


def test_tracing_off_adds_zero_syncs_and_zero_spans(warm_engine, monkeypatch):
    sync = _CountingSync(monkeypatch)
    spans_before = span_allocations()
    r = warm_engine.execute_sql(SQL)
    assert not r.exceptions, r.exceptions
    assert r.trace_info is None
    assert sync.block_calls == 0, (
        "tracing-off dispatch must not add block_until_ready syncs")
    assert sync.device_get_calls == 0, (
        "tracing-off dispatch must not add device_get syncs")
    assert span_allocations() == spans_before, (
        "tracing-off path must allocate zero Span objects")


def test_traced_run_does_sync_and_allocate(warm_engine, monkeypatch):
    """Sanity: the guard watches live sites — tracing ON must trip both."""
    sync = _CountingSync(monkeypatch)
    spans_before = span_allocations()
    r = warm_engine.execute_sql("SET trace = true; " + SQL)
    assert not r.exceptions, r.exceptions
    assert r.trace_info
    assert sync.block_calls > 0
    assert span_allocations() > spans_before


# -- cluster-path guard: cost accounting + health rollup stay off the hot
# -- path (observability PR discipline: with tracing off and no ANALYZE,
# -- a broker query does zero span allocations, zero extra syncs, and
# -- zero store writes — no beacon publish, no scrape work)


CSQL = "SET resultCache = false; SELECT pck, SUM(pcv) FROM pgclu GROUP BY pck"


@pytest.fixture(scope="module")
def warm_cluster(tmp_path_factory):
    from pinot_tpu.cluster import (Broker, ClusterController, PropertyStore,
                                   ServerInstance)
    from pinot_tpu.segment.builder import SegmentBuilder as SB

    d = tmp_path_factory.mktemp("pg_cluster")
    store = PropertyStore()
    controller = ClusterController(store)
    server = ServerInstance(store, "Server_0", backend="host")
    server.start()
    schema = Schema.build("pgclu", dimensions=[("pck", "INT")],
                          metrics=[("pcv", "INT")])
    controller.add_schema(schema.to_json())
    controller.create_table({"tableName": "pgclu", "replication": 1})
    rng = np.random.default_rng(9)
    for i in range(2):
        cols = {"pck": rng.integers(0, 16, 1500).astype(np.int32),
                "pcv": rng.integers(0, 100, 1500).astype(np.int32)}
        name = f"pgclu_{i}"
        SB(schema, segment_name=name).build(cols, d / name)
        controller.add_segment("pgclu_OFFLINE", name,
                               {"location": str(d / name), "numDocs": 1500})
    broker = Broker(store)
    broker.backoff_base_s = 0.001
    for _ in range(2):
        r = broker.execute_sql(CSQL)
        assert not r.exceptions, r.exceptions
    yield store, broker, server
    server.stop()


def test_cluster_off_path_zero_spans_zero_store_writes(warm_cluster,
                                                       monkeypatch):
    store, broker, _ = warm_cluster
    writes = {"n": 0}
    real_set = store.set

    def counting_set(path, value, *a, **kw):
        writes["n"] += 1
        return real_set(path, value, *a, **kw)

    monkeypatch.setattr(store, "set", counting_set)
    spans_before = span_allocations()
    r = broker.execute_sql(CSQL)
    assert not r.exceptions, r.exceptions
    assert r.trace_info is None
    assert span_allocations() == spans_before, (
        "untraced broker query must allocate zero Span objects")
    assert writes["n"] == 0, (
        "untraced broker query must do zero store writes — no state "
        "beacon, no scrape work on the query thread")


def test_sampling_disabled_adds_zero_spans_zero_syncs(warm_cluster,
                                                      monkeypatch):
    """Flight recorder off-path guard: with PINOT_TPU_TRACE_SAMPLE unset
    (and again explicitly 0.0) a broker query allocates zero spans and
    adds zero device syncs — the sampler must stay a cheap decision, not
    an armed trace."""
    _store, broker, _ = warm_cluster
    sync = _CountingSync(monkeypatch)
    for env in (None, "0.0"):
        if env is None:
            monkeypatch.delenv("PINOT_TPU_TRACE_SAMPLE", raising=False)
        else:
            monkeypatch.setenv("PINOT_TPU_TRACE_SAMPLE", env)
        spans_before = span_allocations()
        r = broker.execute_sql(CSQL)
        assert not r.exceptions, r.exceptions
        assert r.trace_info is None
        assert getattr(r, "trace_id", None) is None
        assert span_allocations() == spans_before
    assert sync.block_calls == 0 and sync.device_get_calls == 0


def test_sampled_run_traces_but_ships_plain(warm_cluster, monkeypatch):
    """Sanity for the guard above: sampling armed DOES allocate spans and
    retain the trace — while the client response still ships without it."""
    _store, broker, _ = warm_cluster
    monkeypatch.setenv("PINOT_TPU_TRACE_SAMPLE", "1.0")
    spans_before = span_allocations()
    r = broker.execute_sql(CSQL)
    assert not r.exceptions, r.exceptions
    assert span_allocations() > spans_before
    assert r.trace_info is None, "sampled trace must not ship to the client"
    assert broker.trace_store.get(r.query_id) is not None


def test_warm_dispatch_counts_without_fingerprint_work(warm_engine,
                                                       monkeypatch):
    """The compile registry's warm path must be counter bumps only: no
    span allocations and ZERO family-fingerprint computations (the
    canonical-bytes IR walk happens exclusively on compile-guard misses).
    segmentCache is disabled so the dispatch actually runs."""
    from pinot_tpu.cache import keys as cache_keys
    from pinot_tpu.engine.compile_registry import COMPILE_REGISTRY

    sql = "SET segmentCache = false; " + SQL
    r = warm_engine.execute_sql(sql)  # settle the family
    assert not r.exceptions, r.exceptions
    # count the IR walk itself: family_fingerprint intentionally does not
    # bump fingerprint_computations(), so the guard watches canonical_bytes
    walks = {"n": 0}
    real_cb = cache_keys.canonical_bytes

    def counting_cb(obj):
        walks["n"] += 1
        return real_cb(obj)

    monkeypatch.setattr(cache_keys, "canonical_bytes", counting_cb)
    spans_before = span_allocations()
    d_before = COMPILE_REGISTRY.snapshot()["totalDispatches"]
    r = warm_engine.execute_sql(sql)
    assert not r.exceptions, r.exceptions
    assert COMPILE_REGISTRY.snapshot()["totalDispatches"] > d_before, (
        "warm dispatch must register in the compile registry")
    assert walks["n"] == 0, (
        "warm dispatch must not re-walk the Program IR")
    assert span_allocations() == spans_before


# -- performance-ledger guard: the per-plan ledger records every broker
# -- query as pure counter bumps — zero syncs, zero span allocations,
# -- zero store writes, zero fingerprint (IR-walk) computations, and a
# -- single attribute read for the disarmed exemplar check


def test_ledger_records_warm_query_at_zero_cost(warm_cluster, monkeypatch):
    from pinot_tpu.cache import keys as cache_keys
    from pinot_tpu.engine.perf_ledger import PERF_LEDGER

    store, broker, _ = warm_cluster
    monkeypatch.delenv("PINOT_TPU_TRACE_SAMPLE", raising=False)
    assert PERF_LEDGER.exemplar_armed is False
    sync = _CountingSync(monkeypatch)
    walks = {"n": 0}
    real_cb = cache_keys.canonical_bytes

    def counting_cb(obj):
        walks["n"] += 1
        return real_cb(obj)

    monkeypatch.setattr(cache_keys, "canonical_bytes", counting_cb)
    writes = {"n": 0}
    real_set = store.set

    def counting_set(path, value, *a, **kw):
        writes["n"] += 1
        return real_set(path, value, *a, **kw)

    monkeypatch.setattr(store, "set", counting_set)
    spans_before = span_allocations()

    def ledger_queries():
        return sum(p["totals"]["queries"]
                   for p in PERF_LEDGER.snapshot()["plans"]
                   if p["table"] == "pgclu")

    q_before = ledger_queries()
    r = broker.execute_sql(CSQL)
    assert not r.exceptions, r.exceptions
    assert ledger_queries() == q_before + 1, (
        "the ledger must record every broker query")
    assert sync.block_calls == 0 and sync.device_get_calls == 0, (
        "ledger recording must not add device syncs")
    assert span_allocations() == spans_before, (
        "ledger recording must allocate zero Span objects")
    assert writes["n"] == 0, (
        "ledger persistence belongs to the sentinel scrape, never the "
        "query thread")
    assert walks["n"] == 0, (
        "the ledger key must reuse the result-cache fingerprint or a "
        "crc32 — never a fresh canonical-bytes IR walk")


def test_ledger_memory_bounded_under_fingerprint_churn(warm_cluster,
                                                       monkeypatch):
    """A fingerprint flood (distinct SQL per query) must not grow the
    ledger past its plan cap — batch eviction absorbs the churn."""
    from pinot_tpu.engine.perf_ledger import PERF_LEDGER

    _store, broker, _ = warm_cluster
    PERF_LEDGER.clear()  # drop plans accumulated by earlier test files
    monkeypatch.setattr(PERF_LEDGER, "max_plans", 8)
    for i in range(40):
        r = broker.execute_sql(
            f"SET resultCache = false; SELECT pck, SUM(pcv) FROM pgclu "
            f"WHERE pcv < {1000 + i} GROUP BY pck")
        assert not r.exceptions, r.exceptions
        assert len(PERF_LEDGER) <= 8, (
            "fingerprint churn must stay inside the plan cap")


def test_armed_exemplar_pins_a_trace(warm_cluster, monkeypatch):
    """Sanity for the zero-cost guard: arming exemplars DOES force-trace
    the next matching query and link it to the alert."""
    from pinot_tpu.engine.perf_ledger import ALERTS, PERF_LEDGER

    _store, broker, _ = warm_cluster
    monkeypatch.delenv("PINOT_TPU_TRACE_SAMPLE", raising=False)
    aid, _new = ALERTS.fire("latency-drift", "pgclu-test", "pgclu",
                            "guard sanity", {})
    PERF_LEDGER.arm_exemplars(aid, table="pgclu", count=1)
    try:
        spans_before = span_allocations()
        r = broker.execute_sql(CSQL)
        assert not r.exceptions, r.exceptions
        assert span_allocations() > spans_before, (
            "armed exemplar must force a sampled trace")
        rec = ALERTS.get(aid)
        assert r.query_id in rec["exemplarTraceIds"]
        ent = broker.trace_store.get(r.query_id)
        assert ent and aid in ent["alertIds"] and ent["pinned"]
        assert PERF_LEDGER.exemplar_armed is False, (
            "a one-shot budget must auto-disarm")
    finally:
        PERF_LEDGER.disarm_exemplars()
        ALERTS.resolve("latency-drift", "pgclu-test")


def test_analyze_and_beacon_move_the_new_counters(warm_cluster):
    """Sanity for the guard above: an armed run DOES move the new
    observability counters — ANALYZE allocates spans, the workload
    tracker folds the query in, and an explicit beacon publish writes
    broker state to the store."""
    store, broker, _ = warm_cluster
    spans_before = span_allocations()
    q0 = broker.workload.snapshot()["tables"].get("pgclu", {})
    r = broker.execute_sql(
        "EXPLAIN ANALYZE SELECT pck, SUM(pcv) FROM pgclu GROUP BY pck "
        "LIMIT 7")
    assert not r.exceptions, r.exceptions
    assert span_allocations() > spans_before
    q1 = broker.workload.snapshot()["tables"]["pgclu"]
    assert q1["queries"] > q0.get("queries", 0.0)
    assert q1["tracedQueries"] > q0.get("tracedQueries", 0.0)
    broker.publish_state()
    beacon = store.get(f"/BROKERSTATE/{broker.broker_id}")
    assert beacon and beacon["brokerId"] == broker.broker_id
