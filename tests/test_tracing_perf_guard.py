"""CI perf-structure guard: tracing OFF must cost nothing on the hot path.

Call-count instrumentation, not wall-clock, so it can't flake: after the
query is warm (compile guard satisfied, fused validation settled, planes
resident in HBM), an untraced run must perform ZERO extra
``jax.block_until_ready`` / ``jax.device_get`` calls and allocate ZERO
trace spans — the only tracing cost allowed is the single thread-local
read in ``TRACING.scope``/``active_trace``. A traced run of the same query
is then required to increment both counters, proving the guard actually
watches the instrumented sites.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from pinot_tpu.engine.query_executor import QueryExecutor
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.spi.data_types import Schema
from pinot_tpu.spi.trace import span_allocations

SQL = "SELECT pgk, SUM(pgv) FROM perfguard GROUP BY pgk"


@pytest.fixture(scope="module")
def warm_engine(tmp_path_factory):
    d = tmp_path_factory.mktemp("perfguard")
    # unique column names -> fresh Program -> this module owns its own
    # compile-guard entries regardless of what other tests compiled
    schema = Schema.build("perfguard", dimensions=[("pgk", "INT")],
                         metrics=[("pgv", "INT")])
    rng = np.random.default_rng(7)
    segs = []
    for i in range(4):
        cols = {"pgk": rng.integers(0, 20, 2000).astype(np.int32),
                "pgv": rng.integers(0, 100, 2000).astype(np.int32)}
        SegmentBuilder(schema, segment_name=f"pg_{i}").build(cols, d / f"s{i}")
        segs.append(load_segment(d / f"s{i}"))
    qe = QueryExecutor()
    qe.add_table(schema, segs)
    # warm: first run compiles, second proves the steady state
    for _ in range(2):
        r = qe.execute_sql(SQL)
        assert not r.exceptions, r.exceptions
    return qe


class _CountingSync:
    """Counting wrappers over jax's host-sync entry points."""

    def __init__(self, monkeypatch):
        self.block_calls = 0
        self.device_get_calls = 0
        real_block = jax.block_until_ready
        real_get = jax.device_get

        def counting_block(x):
            self.block_calls += 1
            return real_block(x)

        def counting_get(x):
            self.device_get_calls += 1
            return real_get(x)

        monkeypatch.setattr(jax, "block_until_ready", counting_block)
        monkeypatch.setattr(jax, "device_get", counting_get)


def test_tracing_off_adds_zero_syncs_and_zero_spans(warm_engine, monkeypatch):
    sync = _CountingSync(monkeypatch)
    spans_before = span_allocations()
    r = warm_engine.execute_sql(SQL)
    assert not r.exceptions, r.exceptions
    assert r.trace_info is None
    assert sync.block_calls == 0, (
        "tracing-off dispatch must not add block_until_ready syncs")
    assert sync.device_get_calls == 0, (
        "tracing-off dispatch must not add device_get syncs")
    assert span_allocations() == spans_before, (
        "tracing-off path must allocate zero Span objects")


def test_traced_run_does_sync_and_allocate(warm_engine, monkeypatch):
    """Sanity: the guard watches live sites — tracing ON must trip both."""
    sync = _CountingSync(monkeypatch)
    spans_before = span_allocations()
    r = warm_engine.execute_sql("SET trace = true; " + SQL)
    assert not r.exceptions, r.exceptions
    assert r.trace_info
    assert sync.block_calls > 0
    assert span_allocations() > spans_before
