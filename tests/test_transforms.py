"""Transform function library: civil-date math, device lowering, parity.

Mirrors the reference's transform-function tests (pinot-core/src/test/.../
operator/transform/) plus the BaseQueriesTest differential pattern: every
query shape runs on both backends and must match. Device lowering is
additionally asserted directly (SegmentPlanner must not fall back) so the
differential test can't silently become host-vs-host.
"""

import datetime as dt
import math

import numpy as np
import pytest

from pinot_tpu.engine.plan import SegmentPlanner
from pinot_tpu.engine.query_executor import QueryExecutor
from pinot_tpu.query.parser.sql import parse_sql
from pinot_tpu.query.transforms import (
    _np_datetrunc,
    _np_day,
    _np_dayofweek,
    _np_dayofyear,
    _np_month,
    _np_timestampadd,
    _np_timestampdiff,
    _np_week,
    _np_year,
    eval_scalar,
)
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.spi.data_types import Schema

EPOCH = dt.datetime(1970, 1, 1)


# ---------------------------------------------------------------------------
# civil-date integer arithmetic vs python datetime (oracle)
# ---------------------------------------------------------------------------


def _random_millis(n=500, seed=7):
    rng = np.random.default_rng(seed)
    # 1902..2100, including pre-1970 to exercise floor-division semantics
    return rng.integers(-2_145_916_800_000, 4_102_444_800_000, n, dtype=np.int64)


def test_civil_extraction_matches_datetime():
    ms = _random_millis()
    for m, y_, mo_, d_, dow_, doy_, wk_ in zip(
            ms, _np_year(ms), _np_month(ms), _np_day(ms), _np_dayofweek(ms),
            _np_dayofyear(ms), _np_week(ms)):
        t = EPOCH + dt.timedelta(milliseconds=int(m))
        assert (y_, mo_, d_) == (t.year, t.month, t.day), int(m)
        assert dow_ == t.isocalendar()[2]
        assert doy_ == t.timetuple().tm_yday
        assert wk_ == t.isocalendar()[1]


@pytest.mark.parametrize("unit", ["SECOND", "MINUTE", "HOUR", "DAY", "WEEK",
                                  "MONTH", "QUARTER", "YEAR"])
def test_datetrunc_matches_datetime(unit):
    for m in _random_millis(100, seed=unit.__hash__() % 1000):
        t = EPOCH + dt.timedelta(milliseconds=int(m))
        got = EPOCH + dt.timedelta(milliseconds=int(_np_datetrunc(unit, int(m))))
        if unit == "SECOND":
            want = t.replace(microsecond=0)
        elif unit == "MINUTE":
            want = t.replace(second=0, microsecond=0)
        elif unit == "HOUR":
            want = t.replace(minute=0, second=0, microsecond=0)
        elif unit == "DAY":
            want = t.replace(hour=0, minute=0, second=0, microsecond=0)
        elif unit == "WEEK":
            d0 = t.date() - dt.timedelta(days=t.isocalendar()[2] - 1)
            want = dt.datetime(d0.year, d0.month, d0.day)
        elif unit == "MONTH":
            want = dt.datetime(t.year, t.month, 1)
        elif unit == "QUARTER":
            want = dt.datetime(t.year, ((t.month - 1) // 3) * 3 + 1, 1)
        else:
            want = dt.datetime(t.year, 1, 1)
        assert got == want, (unit, t)


def test_timestamp_add_diff():
    base = int((dt.datetime(2020, 1, 31) - EPOCH).total_seconds() * 1000)
    # month-end clamping: Jan 31 + 1 month = Feb 29 (2020 is a leap year)
    got = EPOCH + dt.timedelta(milliseconds=int(_np_timestampadd("MONTH", 1, base)))
    assert got == dt.datetime(2020, 2, 29)
    assert int(_np_timestampdiff("DAY", base, base + 86_400_000 * 3)) == 3
    a = int((dt.datetime(2020, 1, 15) - EPOCH).total_seconds() * 1000)
    b = int((dt.datetime(2021, 3, 20) - EPOCH).total_seconds() * 1000)
    assert int(_np_timestampdiff("MONTH", a, b)) == 14
    assert int(_np_timestampdiff("YEAR", a, b)) == 1


def test_scalar_forms():
    assert eval_scalar("upper", ["boston"]) == "BOSTON"
    assert eval_scalar("concat", ["a", "b", "-"]) == "a-b"
    assert eval_scalar("length", ["hello"]) == 5
    assert eval_scalar("sha256", ["x"]) == (
        "2d711642b726b04401627ca9fbac32f5c8530fb1903cc4db02258717921a4881")
    assert eval_scalar("regexpextract", ["ab123cd", r"(\d+)", 1, ""]) == "123"


# ---------------------------------------------------------------------------
# end-to-end differential: tpu vs host over a time-series-ish table
# ---------------------------------------------------------------------------

N1, N2 = 800, 600


@pytest.fixture(scope="module")
def table(tmp_path_factory):
    rng = np.random.default_rng(42)
    tmp = tmp_path_factory.mktemp("tsegs")
    schema = Schema.build(
        "events",
        dimensions=[("ts", "LONG"), ("name", "STRING"), ("city", "STRING")],
        metrics=[("val", "DOUBLE"), ("qty", "INT")],
    )
    lo = int((dt.datetime(2019, 1, 1) - EPOCH).total_seconds() * 1000)
    hi = int((dt.datetime(2023, 12, 31) - EPOCH).total_seconds() * 1000)
    names = ["alpha", "Beta", "GAMMA", "delta_x", "Epsilon"]
    cities = ["nyc", "sfo", "chi", "aus"]
    segments = []
    for si, n in enumerate([N1, N2]):
        cols = {
            "ts": rng.integers(lo, hi, n, dtype=np.int64),
            "name": [names[int(rng.integers(len(names)))] for _ in range(n)],
            "city": [cities[int(rng.integers(len(cities)))] for _ in range(n)],
            "val": np.round(rng.random(n) * 1000, 3),
            "qty": rng.integers(1, 100, n).astype(np.int32),
        }
        d = tmp / f"seg_{si}"
        SegmentBuilder(schema, segment_name=f"seg_{si}").build(cols, d)
        segments.append(load_segment(d))
    return schema, segments


def executors(table):
    schema, segments = table
    tpu = QueryExecutor(backend="tpu")
    tpu.add_table(schema, segments)
    host = QueryExecutor(backend="host")
    host.add_table(schema, segments)
    return tpu, host


def assert_same(tpu_resp, host_resp):
    rt, rh = tpu_resp.result_table, host_resp.result_table
    assert rt is not None, f"tpu failed: {tpu_resp.exceptions}"
    assert rh is not None, f"host failed: {host_resp.exceptions}"
    rows_t = sorted(rt.rows, key=repr)
    rows_h = sorted(rh.rows, key=repr)
    assert len(rows_t) == len(rows_h), f"{len(rows_t)} vs {len(rows_h)}"
    for a, b in zip(rows_t, rows_h):
        for x, y in zip(a, b):
            if isinstance(x, float) and isinstance(y, float):
                if math.isnan(x) and math.isnan(y):
                    continue
                assert x == pytest.approx(y, rel=1e-9), (a, b)
            else:
                assert x == y, (a, b)


QUERIES = [
    # datetime extraction as group key (device: civil-date arithmetic)
    "SELECT year(ts), COUNT(*) FROM events GROUP BY year(ts) ORDER BY year(ts) LIMIT 10",
    "SELECT year(ts), month(ts), SUM(val) FROM events GROUP BY year(ts), month(ts) LIMIT 100",
    "SELECT dayOfWeek(ts), COUNT(*) FROM events GROUP BY dayOfWeek(ts) LIMIT 10",
    "SELECT datetrunc('MONTH', ts), COUNT(*) FROM events GROUP BY datetrunc('MONTH', ts) LIMIT 100",
    "SELECT toEpochDays(ts), COUNT(*) FROM events GROUP BY toEpochDays(ts) LIMIT 3000",
    # datetime in filters
    "SELECT COUNT(*) FROM events WHERE year(ts) = 2021",
    "SELECT SUM(qty) FROM events WHERE month(ts) IN (1, 2, 12)",
    "SELECT COUNT(*) FROM events WHERE hour(ts) BETWEEN 9 AND 17",
    # string transforms in filters (dict-LUT path)
    "SELECT COUNT(*) FROM events WHERE upper(name) = 'BETA'",
    "SELECT COUNT(*) FROM events WHERE lower(name) IN ('alpha', 'gamma')",
    "SELECT COUNT(*) FROM events WHERE startsWith(name, 'de') = true",
    "SELECT COUNT(*) FROM events WHERE length(name) > 5",
    "SELECT COUNT(*) FROM events WHERE substr(name, 0, 1) = 'B'",
    # string transforms as group keys (derived dimension remap)
    "SELECT upper(city), COUNT(*) FROM events GROUP BY upper(city) LIMIT 10",
    "SELECT length(name), SUM(qty) FROM events GROUP BY length(name) LIMIT 10",
    "SELECT concat(city, name, '_'), COUNT(*) FROM events GROUP BY concat(city, name, '_') LIMIT 100",
    # numeric transforms in aggregation inputs
    "SELECT SUM(round(val, 10)) FROM events",
    "SELECT MAX(sqrt(val)), MIN(abs(val)) FROM events",
    "SELECT year(ts), AVG(val) FROM events WHERE city = 'nyc' GROUP BY year(ts) LIMIT 10",
    # timestamp arithmetic
    "SELECT COUNT(*) FROM events WHERE timestampDiff('DAY', fromEpochDays(17897), ts) > 365",
    # post-aggregation transforms
    "SELECT city, concat(city, 'x', '-') FROM events GROUP BY city LIMIT 10",
]


@pytest.mark.parametrize("sql", QUERIES)
def test_differential(table, sql):
    tpu, host = executors(table)
    assert_same(tpu.execute_sql(sql), host.execute_sql(sql))


DEVICE_LOWERED = [
    "SELECT year(ts), COUNT(*) FROM events GROUP BY year(ts) LIMIT 10",
    "SELECT COUNT(*) FROM events WHERE upper(name) = 'BETA'",
    "SELECT upper(city), COUNT(*) FROM events GROUP BY upper(city) LIMIT 10",
    "SELECT SUM(round(val, 10)) FROM events",
    "SELECT COUNT(*) FROM events WHERE hour(ts) BETWEEN 9 AND 17",
    "SELECT datetrunc('MONTH', ts), COUNT(*) FROM events GROUP BY datetrunc('MONTH', ts) LIMIT 100",
]


@pytest.mark.parametrize("sql", DEVICE_LOWERED)
def test_device_lowering_does_not_fall_back(table, sql):
    _, segments = table
    q = parse_sql(sql)
    plan = SegmentPlanner(q, segments[0]).plan()  # raises on fallback
    assert plan.program is not None


def test_order_by_transform_not_in_select_list(table):
    # hidden order-by column must be appended per segment then projected away
    tpu, host = executors(table)
    sql = "SELECT name FROM events WHERE city = 'nyc' ORDER BY upper(name), name LIMIT 15"
    rt = tpu.execute_sql(sql)
    rh = host.execute_sql(sql)
    assert not rt.exceptions and not rh.exceptions, (rt.exceptions, rh.exceptions)
    assert rt.result_table.schema.column_names == ["name"]
    assert rt.result_table.rows == rh.result_table.rows


def test_coalesce_inside_transform_falls_back_correctly(table):
    # eval_expr_np must refuse coalesce (dict space has no per-doc nullness);
    # the auto backend falls back to host and returns correct results
    _, segments = table
    schema = table[0]
    ex = QueryExecutor(backend="auto")
    ex.add_table(schema, segments)
    r = ex.execute_sql("SELECT COUNT(*) FROM events WHERE upper(coalesce(name, 'zz')) = 'BETA'")
    assert not r.exceptions, r.exceptions
    host = QueryExecutor(backend="host")
    host.add_table(schema, segments)
    rh = host.execute_sql("SELECT COUNT(*) FROM events WHERE upper(name) = 'BETA'")
    assert r.result_table.rows == rh.result_table.rows


def test_selection_with_transforms(table):
    tpu, host = executors(table)
    sql = ("SELECT name, upper(name), length(name) FROM events "
           "WHERE city = 'sfo' ORDER BY length(name), name LIMIT 20")
    rt = tpu.execute_sql(sql).result_table
    rh = host.execute_sql(sql).result_table
    assert rt is not None and rh is not None
    assert rt.rows == rh.rows
