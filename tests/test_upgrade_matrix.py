"""Two-way rolling-upgrade verifier.

Reference analogue: compatibility-verifier/compCheck.sh + its README —
build two git revisions and verify artifacts written by each are readable
by the other, plus a live mixed-version cluster. Here:

  OLD→NEW  the previous round's code (git worktree of OLD_REV) builds a
           segment, DataTable blobs, and serialized MSE plan stages; the
           CURRENT code reads all three and re-derives identical results.
  NEW→OLD  current code writes the same artifact set; the OLD code reads.
  MIXED    an OLD-code server process joins a NEW-code cluster through
           the networked property store and serves segments for a
           NEW-code broker's scatter/gather — the live wire protocol.

The OLD revision floats forward each round (it is "the previous release"),
unlike tests/golden/ whose committed bytes pin the oldest supported
format.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
# Round-4 final commit — the "previous release" for this round.
OLD_REV = "7104746"

# The version-portable writer/reader. Runs under BOTH revisions, so only
# APIs that exist in OLD_REV may appear here.
CHILD = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PINOT_TPU_DISABLE_NATIVE"] = "1"
import numpy as np

mode, art = sys.argv[1], sys.argv[2]

from pinot_tpu.cluster import datatable as dtmod
from pinot_tpu.engine.query_executor import QueryExecutor
from pinot_tpu.engine.reduce import BrokerReducer
from pinot_tpu.mse.fragmenter import fragment
from pinot_tpu.mse.logical import LogicalPlanner
from pinot_tpu.mse.parser import parse_relational
from pinot_tpu.mse.plan_serde import stage_from_json, stage_to_json
from pinot_tpu.query.parser.sql import parse_sql
from pinot_tpu.segment.builder import SegmentBuilder
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.spi.data_types import Schema

SCHEMA = Schema.build(
    "up",
    dimensions=[("s", "STRING"), ("i", "INT")],
    metrics=[("m", "INT"), ("d", "DOUBLE")])

AGG_SQL = "SELECT SUM(m), COUNT(*), DISTINCTCOUNT(s) FROM up WHERE i < 40"
GRP_SQL = "SELECT s, SUM(m), AVG(d) FROM up GROUP BY s ORDER BY s LIMIT 50"
MSE_SQL = ("SELECT a.s, SUM(a.m) FROM up a JOIN up b ON a.i = b.i "
           "GROUP BY a.s LIMIT 50")


def rows_of(resp):
    return [[v if not isinstance(v, float) else round(v, 6) for v in r]
            for r in resp.result_table.rows]


def build_data():
    rng = np.random.default_rng(42)
    n = 500
    return {
        "s": np.asarray(["a", "b", "c", "d"], dtype=object)[
            rng.integers(0, 4, n)],
        "i": rng.integers(0, 60, n).astype(np.int32),
        "m": rng.integers(-100, 1000, n).astype(np.int32),
        "d": np.round(rng.random(n) * 10, 4),
    }


if mode == "write":
    out = {}
    cols = build_data()
    SegmentBuilder(SCHEMA, segment_name="up0").build(cols, art + "/segment")
    seg = load_segment(art + "/segment")
    qe = QueryExecutor(backend="host")
    qe.add_table(SCHEMA, [seg])
    for tag, sql in (("agg", AGG_SQL), ("grp", GRP_SQL)):
        combined, stats = qe.execute_segments(parse_sql(sql), [seg])
        blob = dtmod.encode(combined, stats)
        open(f"{art}/dt_{tag}.bin", "wb").write(blob)
        out[f"rows_{tag}"] = rows_of(qe.execute_sql(sql))
    q = parse_relational(MSE_SQL)
    plan = LogicalPlanner(q, {"up": SCHEMA.column_names()}).plan()
    stages = fragment(plan)
    json.dump([stage_to_json(st) for st in stages],
              open(art + "/plan.json", "w"))
    out["num_stages"] = len(stages)
    json.dump(out, open(art + "/expect.json", "w"))
    print("WRITE OK")
elif mode == "read":
    expect = json.load(open(art + "/expect.json"))
    seg = load_segment(art + "/segment")
    assert seg.num_docs == 500, seg.num_docs
    qe = QueryExecutor(backend="host")
    qe.add_table(SCHEMA, [seg])
    for tag, sql in (("agg", AGG_SQL), ("grp", GRP_SQL)):
        got = rows_of(qe.execute_sql(sql))
        assert got == expect[f"rows_{tag}"], (tag, got, expect[f"rows_{tag}"])
        # the DataTable bytes the other version wrote must decode AND
        # broker-reduce to the same result rows
        combined, stats = dtmod.decode(open(f"{art}/dt_{tag}.bin", "rb").read())
        table = BrokerReducer(SCHEMA).reduce(parse_sql(sql), combined)
        red = [[v if not isinstance(v, float) else round(v, 6) for v in r]
               for r in table.rows]
        assert red == expect[f"rows_{tag}"], (tag, red)
    stages = [stage_from_json(d) for d in json.load(open(art + "/plan.json"))]
    assert len(stages) == expect["num_stages"]
    roundtrip = [stage_to_json(st) for st in stages]
    assert [d["stage_id"] for d in roundtrip] == \
        [d["stage_id"] for d in json.load(open(art + "/plan.json"))]
    print("READ OK")
else:
    raise SystemExit(f"unknown mode {mode}")
"""

MIXED_SERVER = r"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PINOT_TPU_DISABLE_NATIVE"] = "1"
from pinot_tpu.cluster.remote_store import RemoteStore
from pinot_tpu.cluster.server import ServerInstance

host, port = sys.argv[1], int(sys.argv[2])
store = RemoteStore(host, port)
server = ServerInstance(store, "OldServer_0", backend="host")
server.start()
print("SERVER UP", flush=True)
try:
    while store.get("/TEST/STOP") is None:
        time.sleep(0.05)
finally:
    server.stop()
    store.close()
"""


def _clean_env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # the axon site hook dials the TPU relay at interpreter startup and
    # hangs children when the tunnel is down
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p)
    return env


def _run_child(code: str, args: list[str], pythonpath: Path, timeout=300):
    env = _clean_env()
    env["PYTHONPATH"] = str(pythonpath) + (
        os.pathsep + env["PYTHONPATH"] if env["PYTHONPATH"] else "")
    r = subprocess.run([sys.executable, "-c", code, *args],
                       capture_output=True, text=True, timeout=timeout,
                       env=env, cwd=str(pythonpath))
    assert r.returncode == 0, \
        f"child failed under {pythonpath}:\n{r.stdout[-800:]}\n{r.stderr[-2000:]}"
    return r.stdout


@pytest.fixture(scope="module")
def old_checkout(tmp_path_factory):
    d = tmp_path_factory.mktemp("oldrev") / "repo"
    r = subprocess.run(
        ["git", "-C", str(REPO), "worktree", "add", "--detach", str(d),
         OLD_REV],
        capture_output=True, text=True, timeout=120)
    if r.returncode != 0:
        pytest.skip(f"cannot create {OLD_REV} worktree: {r.stderr[-300:]}")
    yield d
    subprocess.run(["git", "-C", str(REPO), "worktree", "remove", "--force",
                    str(d)], capture_output=True, timeout=120)


def test_old_writes_new_reads(old_checkout, tmp_path):
    art = tmp_path / "o2n"
    art.mkdir()
    assert "WRITE OK" in _run_child(CHILD, ["write", str(art)], old_checkout)
    assert "READ OK" in _run_child(CHILD, ["read", str(art)], REPO)


def test_new_writes_old_reads(old_checkout, tmp_path):
    art = tmp_path / "n2o"
    art.mkdir()
    assert "WRITE OK" in _run_child(CHILD, ["write", str(art)], REPO)
    assert "READ OK" in _run_child(CHILD, ["read", str(art)], old_checkout)


def test_mixed_cluster_old_server_new_broker(old_checkout, tmp_path):
    """Live wire: previous-release server process inside a current-release
    cluster (new store/controller/broker), serving real queries."""
    import numpy as np

    from pinot_tpu.cluster import Broker, ClusterController
    from pinot_tpu.cluster.remote_store import PropertyStoreServer
    from pinot_tpu.segment.builder import SegmentBuilder
    from pinot_tpu.spi.data_types import Schema

    schema = Schema.build(
        "mx", dimensions=[("g", "STRING")], metrics=[("v", "INT")])
    server_store = PropertyStoreServer()
    store = server_store.store
    controller = ClusterController(store)
    broker = Broker(store)
    controller.add_schema(schema.to_json())

    host, port = server_store.address
    env = _clean_env()
    env["PYTHONPATH"] = str(old_checkout) + (
        os.pathsep + env["PYTHONPATH"] if env["PYTHONPATH"] else "")
    child = subprocess.Popen(
        [sys.executable, "-c", MIXED_SERVER, host, str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        cwd=str(old_checkout))
    try:
        deadline = time.time() + 60
        while "OldServer_0" not in store.children("/LIVEINSTANCES"):
            assert child.poll() is None, child.stderr.read()[-2000:]
            assert time.time() < deadline, "old server never joined"
            time.sleep(0.05)

        table = controller.create_table({"tableName": "mx", "replication": 1})
        rng = np.random.default_rng(0)
        n = 400
        cols = {"g": np.asarray(["x", "y", "z"], dtype=object)[
                    rng.integers(0, 3, n)],
                "v": rng.integers(0, 100, n).astype(np.int32)}
        SegmentBuilder(schema, segment_name="mx0").build(cols, tmp_path / "mx0")
        controller.add_segment(table, "mx0",
                               {"location": str(tmp_path / "mx0"),
                                "numDocs": n})
        deadline = time.time() + 60
        while "OldServer_0" not in (
                store.get(f"/EXTERNALVIEW/{table}") or {}).get("mx0", {}):
            assert child.poll() is None, child.stderr.read()[-2000:]
            assert time.time() < deadline, "segment never online on old server"
            time.sleep(0.05)

        want = {}
        for g, v in zip(cols["g"], cols["v"]):
            want[g] = want.get(g, 0) + int(v)
        resp = broker.execute_sql(
            "SELECT g, SUM(v) FROM mx GROUP BY g LIMIT 10")
        assert not resp.exceptions, resp.exceptions
        assert {r[0]: r[1] for r in resp.result_table.rows} == want
    finally:
        store.set("/TEST/STOP", True)
        try:
            child.wait(timeout=15)
        except subprocess.TimeoutExpired:
            child.kill()
        server_store.close()