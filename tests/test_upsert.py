"""Upsert & dedup tests.

Reference pattern: upsert unit tests in pinot-segment-local
(ConcurrentMapPartitionUpsertMetadataManagerTest, PartialUpsertHandlerTest)
plus the realtime upsert integration suites.
"""

from __future__ import annotations

import numpy as np
import pytest

from pinot_tpu.engine.query_executor import QueryExecutor
from pinot_tpu.realtime.manager import RealtimeTableDataManager
from pinot_tpu.segment.mutable import MutableSegment
from pinot_tpu.spi.data_types import Schema

from pinot_tpu.spi.table_config import (
    DedupConfig,
    IngestionConfig,
    TableConfig,
    UpsertConfig,
)
from pinot_tpu.upsert import (
    PartialUpsertHandler,
    TableDedupManager,
    TableUpsertMetadataManager,
)

SCHEMA = Schema.build(
    "events",
    dimensions=[("pk", "STRING"), ("city", "STRING")],
    metrics=[("clicks", "INT")],
    date_times=[("ts", "LONG")],
    primary_key_columns=["pk"])


def _cfg(mode="FULL", strategies=None, dedup=False):
    return TableConfig(
        table_name="events",
        upsert=UpsertConfig(mode=mode,
                            partial_upsert_strategies=strategies or {},
                            comparison_columns=["ts"]),
        dedup=DedupConfig(enabled=dedup))


def _mk_segment(name="seg0"):
    return MutableSegment(SCHEMA, name)


def test_full_upsert_latest_wins():
    mgr = TableUpsertMetadataManager(SCHEMA, _cfg())
    seg = _mk_segment()
    rows = [
        {"pk": "a", "city": "sf", "clicks": 1, "ts": 100},
        {"pk": "b", "city": "ny", "clicks": 2, "ts": 100},
        {"pk": "a", "city": "la", "clicks": 3, "ts": 200},  # newer → wins
        {"pk": "b", "city": "aus", "clicks": 4, "ts": 50},  # older → loses
    ]
    for r in rows:
        d = seg.index(r)
        mgr.add_record(seg, d, r)
    mask = seg.valid_doc_ids.mask(seg.num_docs)
    assert list(mask) == [False, True, True, False]
    assert mgr.num_primary_keys() == 2


def test_upsert_tie_goes_to_later_arrival():
    mgr = TableUpsertMetadataManager(SCHEMA, _cfg())
    seg = _mk_segment()
    for r in [{"pk": "a", "city": "sf", "clicks": 1, "ts": 100},
              {"pk": "a", "city": "la", "clicks": 2, "ts": 100}]:
        d = seg.index(r)
        mgr.add_record(seg, d, r)
    assert list(seg.valid_doc_ids.mask(2)) == [False, True]


def test_query_sees_only_valid_docs():
    mgr = TableUpsertMetadataManager(SCHEMA, _cfg())
    seg = _mk_segment()
    for i, r in enumerate([
            {"pk": "a", "city": "sf", "clicks": 10, "ts": 1},
            {"pk": "a", "city": "sf", "clicks": 20, "ts": 2},
            {"pk": "b", "city": "ny", "clicks": 5, "ts": 1}]):
        d = seg.index(r)
        mgr.add_record(seg, d, r)
    qe = QueryExecutor(backend="host")
    qe.add_table(SCHEMA, [seg])
    r = qe.execute_sql("SELECT SUM(clicks), COUNT(*) FROM events")
    assert not r.exceptions, r.exceptions
    assert r.result_table.rows[0] == [25.0, 2]
    r = qe.execute_sql("SELECT city, SUM(clicks) FROM events GROUP BY city ORDER BY city")
    assert [list(x) for x in r.result_table.rows] == [["ny", 5.0], ["sf", 20.0]]


def test_query_valid_docs_device_path():
    """Device plan ANDs the validity plane as a MaskParam (immutable segment
    on the virtual-device jax backend)."""
    from pinot_tpu.segment.builder import SegmentBuilder
    from pinot_tpu.segment.loader import load_segment

    mgr = TableUpsertMetadataManager(SCHEMA, _cfg())
    mseg = _mk_segment()
    rows = [
        {"pk": "a", "city": "sf", "clicks": 10, "ts": 1},
        {"pk": "a", "city": "sf", "clicks": 20, "ts": 2},
        {"pk": "b", "city": "ny", "clicks": 5, "ts": 1},
    ]
    for r in rows:
        d = mseg.index(r)
        mgr.add_record(mseg, d, r)
    # commit: convert preserving order, transfer validity
    from pinot_tpu.realtime.converter import RealtimeSegmentConverter

    out = RealtimeSegmentConverter(SCHEMA, _cfg(), preserve_doc_order=True)
    import tempfile

    d2 = tempfile.mkdtemp()
    out.convert(mseg, d2 + "/s")
    committed = load_segment(d2 + "/s")
    mgr.replace_segment(mseg, committed)
    assert committed.valid_doc_ids is not None
    qe = QueryExecutor(backend="tpu")
    qe.add_table(SCHEMA, [committed])
    r = qe.execute_sql("SELECT SUM(clicks), COUNT(*) FROM events")
    assert not r.exceptions, r.exceptions
    assert r.result_table.rows[0] == [25.0, 2]


def test_partial_upsert_strategies():
    h = PartialUpsertHandler(
        {"clicks": "INCREMENT", "city": "IGNORE", "tags": "UNION"},
        exclude={"pk", "ts"})
    prev = {"pk": "a", "ts": 1, "clicks": 5, "city": "sf", "tags": ["x"]}
    new = {"pk": "a", "ts": 2, "clicks": 3, "city": "la", "tags": ["x", "y"]}
    merged = h.merge(prev, new)
    assert merged["clicks"] == 8
    assert merged["city"] == "sf"
    assert merged["tags"] == ["x", "y"]
    assert merged["ts"] == 2


def test_partial_upsert_null_keeps_previous():
    h = PartialUpsertHandler({}, exclude={"pk"})
    merged = h.merge({"pk": "a", "city": "sf"}, {"pk": "a", "city": None})
    assert merged["city"] == "sf"


def test_partial_upsert_through_manager():
    mgr = TableUpsertMetadataManager(SCHEMA, _cfg(
        mode="PARTIAL", strategies={"clicks": "INCREMENT"}))
    seg = _mk_segment()
    r1 = {"pk": "a", "city": "sf", "clicks": 5, "ts": 1}
    r1 = mgr.process_row(seg, r1)
    d = seg.index(r1)
    mgr.add_record(seg, d, r1)
    r2 = {"pk": "a", "city": None, "clicks": 3, "ts": 2}
    r2 = mgr.process_row(seg, r2)
    assert r2["clicks"] == 8
    assert r2["city"] == "sf"
    d = seg.index(r2)
    mgr.add_record(seg, d, r2)
    assert list(seg.valid_doc_ids.mask(2)) == [False, True]


def test_dedup_drops_duplicates():
    mgr = TableDedupManager(SCHEMA, _cfg(mode="NONE", dedup=True))
    seg = _mk_segment()
    assert mgr.process_row(seg, {"pk": "a", "clicks": 1}) is not None
    assert mgr.process_row(seg, {"pk": "a", "clicks": 2}) is None
    assert mgr.process_row(seg, {"pk": "b", "clicks": 3}) is not None
    assert mgr.num_primary_keys() == 2


def test_realtime_upsert_end_to_end(tmp_path):
    """Stream → mutable upsert → commit → immutable with transferred
    validity; restart rebuilds metadata (reference: upsert LLC realtime)."""
    from pinot_tpu.spi.stream import GLOBAL_STREAM_REGISTRY

    rows = [
        {"pk": "a", "city": "sf", "clicks": 1, "ts": 100},
        {"pk": "b", "city": "ny", "clicks": 2, "ts": 100},
        {"pk": "a", "city": "la", "clicks": 3, "ts": 200},
    ]
    GLOBAL_STREAM_REGISTRY.create_topic("upsert_events", 1)
    GLOBAL_STREAM_REGISTRY.publish("upsert_events", rows)
    cfg = TableConfig(
        table_name="events",
        upsert=UpsertConfig(mode="FULL", comparison_columns=["ts"]),
        ingestion=IngestionConfig(stream_configs={
            "streamType": "inmemory", "stream.inmemory.topic.name": "upsert_events",
            "realtime.segment.flush.threshold.rows": 1000,
        }))
    mgr = RealtimeTableDataManager(SCHEMA, cfg, tmp_path / "rt")
    mgr.start()
    try:
        import time as _t

        deadline = _t.time() + 10
        while _t.time() < deadline:
            if mgr.total_docs() >= 3:
                break
            _t.sleep(0.05)
        qe = QueryExecutor(backend="host")
        qe.add_table(SCHEMA, mgr.segments)
        r = qe.execute_sql("SELECT COUNT(*), SUM(clicks) FROM events")
        assert not r.exceptions, r.exceptions
        assert r.result_table.rows[0] == [2, 5.0]
        # commit and re-query through the committed segment
        committed = mgr.force_commit()
        assert committed
        r = qe.execute_sql("SELECT COUNT(*), SUM(clicks) FROM events")
        assert r.result_table.rows[0] == [2, 5.0]
    finally:
        mgr.stop()

    # restart: metadata rebuilt from committed segments
    mgr2 = RealtimeTableDataManager(SCHEMA, cfg, tmp_path / "rt")
    mgr2.start()
    try:
        qe2 = QueryExecutor(backend="host")
        qe2.add_table(SCHEMA, mgr2.segments)
        r = qe2.execute_sql("SELECT COUNT(*), SUM(clicks) FROM events")
        assert not r.exceptions, r.exceptions
        assert r.result_table.rows[0] == [2, 5.0]
        assert mgr2.pk_manager.num_primary_keys() == 2
    finally:
        mgr2.stop()


# -- TTL, delete column, consistency mode (reference UpsertConfig additions) --


def _cfg_ext(**kw):
    return TableConfig(
        table_name="events",
        upsert=UpsertConfig(mode="FULL", comparison_columns=["ts"], **kw))


def test_delete_record_column_tombstones():
    schema = Schema.build(
        "events",
        dimensions=[("pk", "STRING"), ("city", "STRING")],
        metrics=[("clicks", "INT"), ("deleted", "INT")],
        date_times=[("ts", "LONG")],
        primary_key_columns=["pk"])
    mgr = TableUpsertMetadataManager(
        schema, _cfg_ext(delete_record_column="deleted"))
    seg = MutableSegment(schema, "s0")
    rows = [
        {"pk": "a", "city": "sf", "clicks": 1, "deleted": 0, "ts": 100},
        {"pk": "a", "city": "", "clicks": 0, "deleted": 1, "ts": 200},  # delete
        {"pk": "a", "city": "la", "clicks": 2, "deleted": 0, "ts": 150},  # older than delete
        {"pk": "a", "city": "ch", "clicks": 3, "deleted": 0, "ts": 300},  # resurrects
    ]
    for r in rows:
        d = seg.index(dict(r))
        mgr.add_record(seg, d, r)
    mask = list(seg.valid_doc_ids.mask(seg.num_docs))
    assert mask == [False, False, False, True]
    assert mgr.num_primary_keys() == 1


def test_metadata_ttl_drops_old_keys():
    mgr = TableUpsertMetadataManager(SCHEMA, _cfg_ext(metadata_ttl=100))
    seg = _mk_segment()
    for r in [{"pk": "old", "city": "sf", "clicks": 1, "ts": 100},
              {"pk": "mid", "city": "ny", "clicks": 2, "ts": 240},
              {"pk": "new", "city": "la", "clicks": 3, "ts": 300}]:
        d = seg.index(dict(r))
        mgr.add_record(seg, d, r)
    assert mgr.num_primary_keys() == 3
    dropped = mgr.remove_expired_metadata()
    # watermark 300, ttl 100 → floor 200: "old" (100) expires
    assert dropped == 1
    assert mgr.num_primary_keys() == 2
    # validity is untouched — expired keys stay queryable
    assert int(seg.valid_doc_ids.mask(seg.num_docs).sum()) == 3


def test_deleted_keys_ttl():
    schema = Schema.build(
        "events",
        dimensions=[("pk", "STRING")],
        metrics=[("deleted", "INT")],
        date_times=[("ts", "LONG")],
        primary_key_columns=["pk"])
    mgr = TableUpsertMetadataManager(
        schema, _cfg_ext(delete_record_column="deleted",
                         deleted_keys_ttl=50))
    seg = MutableSegment(schema, "s0")
    for r in [{"pk": "a", "deleted": 0, "ts": 100},
              {"pk": "a", "deleted": 1, "ts": 110},
              {"pk": "b", "deleted": 0, "ts": 200}]:
        d = seg.index(dict(r))
        mgr.add_record(seg, d, r)
    assert len(mgr._deleted) == 1
    assert mgr.remove_expired_metadata() == 1  # tombstone (110) < 200-50
    assert len(mgr._deleted) == 0


def test_sync_consistency_shares_locks():
    mgr = TableUpsertMetadataManager(SCHEMA, _cfg_ext(consistency_mode="SYNC"))
    seg_a, seg_b = _mk_segment("a"), _mk_segment("b")
    r1 = {"pk": "k", "city": "sf", "clicks": 1, "ts": 100}
    d = seg_a.index(dict(r1))
    mgr.add_record(seg_a, d, r1)
    r2 = {"pk": "k", "city": "ny", "clicks": 2, "ts": 200}
    d = seg_b.index(dict(r2))
    mgr.add_record(seg_b, d, r2)
    # both planes share the manager's lock: a mask snapshot taken while an
    # update holds the lock cannot observe the half-applied state
    assert seg_a.valid_doc_ids._lock is mgr._lock
    assert seg_b.valid_doc_ids._lock is mgr._lock
    assert list(seg_a.valid_doc_ids.mask(1)) == [False]
    assert list(seg_b.valid_doc_ids.mask(1)) == [True]


def test_out_of_order_delete_does_not_clobber_newer_row():
    """A late delete row older than the live row must lose (reference:
    deleteRecordColumn resolves through the comparison column)."""
    schema = Schema.build(
        "events",
        dimensions=[("pk", "STRING")],
        metrics=[("v", "INT"), ("deleted", "INT")],
        date_times=[("ts", "LONG")],
        primary_key_columns=["pk"])
    mgr = TableUpsertMetadataManager(
        schema, _cfg_ext(delete_record_column="deleted"))
    seg = MutableSegment(schema, "s0")
    for r in [{"pk": "a", "v": 1, "deleted": 0, "ts": 300},
              {"pk": "a", "v": 0, "deleted": 1, "ts": 200}]:  # late delete
        d = seg.index(dict(r))
        mgr.add_record(seg, d, r)
    assert list(seg.valid_doc_ids.mask(2)) == [True, False]
    assert mgr.num_primary_keys() == 1
    # and a late delete can't replace a NEWER tombstone
    for r in [{"pk": "b", "v": 0, "deleted": 1, "ts": 500},
              {"pk": "b", "v": 0, "deleted": 1, "ts": 400}]:
        d = seg.index(dict(r))
        mgr.add_record(seg, d, r)
    assert mgr._deleted[("b",)] == 500
